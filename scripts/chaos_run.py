"""Crash-recovery chaos harness: prove exactly-once aggregation under
injected faults (docs/ROBUSTNESS.md; the standing answer to "what
breaks when X dies").

Topology — chosen so the component being killed is the REAL binary
while everything else stays fast:

  - leader + helper DAP servers run in-process (DapServer threads over
    loopback HTTP) with file-backed SQLite datastores in a temp dir,
    so the aggregation job drivers cross a real process + HTTP + DB
    boundary;
  - the aggregation job driver — the thing that crashes — runs as the
    real `python -m janus_tpu.bin.aggregation_job_driver` binary
    against the leader database file, armed via JANUS_FAILPOINTS;
  - the job creator and collection job driver run in-process.

Deterministic schedule (all probabilistic faults are count-budgeted):

  1. upload N reports through the real Client; the admitted
     measurements are the ground truth.
  2. driver A boots with
       datastore.commit.step_agg_job_write=crash:1.0,count=1
     — it steps the job: the helper aggregates and acks the init, and
     the leader dies (os._exit, the SIGKILL analog) BEFORE its own
     write commits. Assert exit code CRASH_EXIT_CODE and a still-held
     lease.
  3. driver B boots into a storm:
       env  helper.request=error:1.0,count=2   (transport failures)
            datastore.commit=error:0.2          (transient tx faults,
                                                 absorbed by run_tx)
       harness-side helper.aggregate=error:1.0,count=2 (real HTTP 500s
                                                 from the helper)
     Its outbound circuit must open, the job steps back (lease
     released early, attempt refunded), the breaker half-opens and
     closes once the storm budget is spent, and the job completes —
     the helper's request-hash dedup makes the replayed init
     idempotent. The lease must be reacquired within the lease TTL.
  4. (full schedule only) a second batch + driver C with
       datastore.post_commit.step_agg_job_write=crash:1.0,count=1
     — death AFTER the commit, before anything was acked — then a
     clean driver D that must find nothing left to redo.
  5. collect through the real Collector and assert the aggregate
     equals the ground truth EXACTLY (count and sum: no loss, no
     double-count), the breaker cycle is visible in
     janus_outbound_circuit_state / _transitions_total and on
     /statusz, and driver B SIGTERM-drains cleanly.

A second scenario, `--scenario db_outage`, proves DATASTORE-outage
survival (docs/ROBUSTNESS.md "Datastore outages"): under a sustained
upload load, the leader's database is taken down via the
`datastore.connect` failpoint (scoped to the leader's store — no real
process is killed). Invariants:

  - every upload acked 201 before, DURING and after the outage window
    is present exactly once in the final collected aggregate — during
    the outage the acks rest on the durable spill journal's fsync;
  - the datastore supervisor walks up → degraded → down → recovering →
    up, `/readyz` flips 200 → 503 (with a JSON reason) → 200 while
    `/healthz` stays live, and aggregate-step routes shed 503 while
    the store is down;
  - on recovery the journal drains to empty (replay through the write
    batcher, report-id dedup = exactly-once) and is truncated;
  - while the datastore is healthy the armed-but-idle journal performs
    ZERO fsyncs — the hot path is unchanged.

A third scenario, `--scenario device_hang`, proves the DEADLINE-AWARE
DEVICE PATH (docs/ROBUSTNESS.md "Device hangs & deadlines"): the real
aggregation job driver binary runs with `engine.dispatch=hang,count=1`
armed — its first device dispatch wedges forever, exactly like a hung
XLA dispatch / tunnel stall. Invariants:

  - the hung step never outlives its lease: the dispatch watchdog
    abandons the dispatch within the lease budget and the job steps
    back (`janus_job_step_back_total{reason="device_hang"}`), releasing
    the lease BEFORE its expiry;
  - the abandoned thread is visible (`janus_hung_dispatches_total`,
    `janus_abandoned_dispatch_threads` under the cap, a live stack dump
    in /statusz `device_watchdog.stalled`) and the engine transitions
    device → quarantined → (canary recompile + probe) → device, all
    observed live over the driver's /metrics + /statusz;
  - interim work lands through the host fallback while quarantined, and
    the final collection equals the admitted ground truth exactly;
  - the driver SIGTERM-drains cleanly (release_hangs unparks the
    modeled wedge on shutdown).

A fifth scenario, `--scenario resident`, proves the RESIDENT
AGGREGATE STATE flush contract (docs/ARCHITECTURE.md "Resident
aggregate state"): the real driver binary runs with
`resident_accumulators` enabled, a one-slot `resident_max_bytes`, and
`engine.dispatch=hang,count=1,after=4` armed. Invariants: an LRU
eviction flushes through the write-tx path live
(`janus_engine_resident_flushes_total{reason="eviction"}`), the
mid-stream quarantine's flusher sweep writes the surviving slot out
(`reason="quarantine"`) while the wedged job re-steps on the host
path, a post-restore job lands resident and SIGTERM drains it, no
flush reports `outcome="lost"`, and BOTH tasks' collections equal
their admitted ground truths exactly.

A further scenario, `--scenario peer_outage`, proves PEER-outage
survival (docs/ARCHITECTURE.md "Surviving the other aggregator"): the
REAL aggregation + collection job driver binaries reach the in-process
helper only through a core/netsim.py FaultProxy, and the wire is
degraded toxiproxy-style. Invariants:

  - clean baseline traffic flows through the proxy and aggregates
    exactly;
  - a full blackhole longer than the breaker-open threshold keeps
    uploads at 201 (the leader is untouched) while BOTH driver
    binaries open their breakers, step back (`reason="circuit_open"`,
    bounded), then PARK: claim transactions stop cold
    (`janus_lease_acquire_tx_total` frozen), `janus_peer_parked` = 1,
    `janus_peer_outage_seconds_total` grows, `/statusz` grows a
    `peer_health` section, and `janus_lease_conflicts_total` stays 0;
  - when the wire heals, the cheap half-open probe
    (`janus_peer_probes_total{outcome="alive"}`) closes the breaker,
    both drivers resume, and the parked work drains;
  - a slow-drip (slicer) response trips the wall-clock body budget and
    a mid-body truncation retries as a torn connection — neither
    wedges a worker, both lanes complete;
  - (full schedule) latency+jitter and flaky mid-request reset lanes
    also complete;
  - the final collections equal the admitted ground truth EXACTLY and
    both binaries SIGTERM-drain cleanly.

Usage:
    python scripts/chaos_run.py --smoke --json   # fast deterministic
    python scripts/chaos_run.py --json           # full schedule (slow)
    python scripts/chaos_run.py --scenario db_outage --smoke --json
    python scripts/chaos_run.py --scenario device_hang --smoke --json
    python scripts/chaos_run.py --scenario resident --smoke --json
    python scripts/chaos_run.py --scenario peer_outage --smoke --json

Exit code 0 iff every invariant held; the result JSON rides on stdout
(bench.py --dry-run embeds the smokes as its chaos_smoke and
db_outage_smoke phases).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import secrets
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# single-device CPU everywhere, shared persistent compile cache: the
# harness pre-warms the engine programs so the driver subprocesses load
# them from disk instead of paying a cold jit inside a short lease
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "", _flags
).strip()

CRASH_SCHEDULE = "datastore.commit.step_agg_job_write=crash:1.0,count=1"
POST_COMMIT_CRASH_SCHEDULE = (
    "datastore.post_commit.step_agg_job_write=crash:1.0,count=1"
)
STORM_SCHEDULE = "helper.request=error:1.0,count=2;datastore.commit=error:0.2"
HELPER_5XX_SCHEDULE = "helper.aggregate=error:1.0,count=2"
# full datastore outage, scoped to the store whose failpoint_scope is
# "leader" (the harness names the leader's store; the in-process
# helper's store keeps its default scope and stays up)
DB_OUTAGE_SCHEDULE = "datastore.connect.leader=error:1.0"
# the driver's first device dispatch wedges FOREVER (released only by
# the stopper): the hung-XLA-dispatch model for --scenario device_hang
DEVICE_HANG_SCHEDULE = "engine.dispatch=hang,count=1"
# --scenario pipeline: stretch every helper RTT so the stage pipeline
# has a real window to overlap device work with (loopback RTTs are
# otherwise microseconds and the overlap proof would be flaky)
PIPELINE_RTT_SCHEDULE = "helper.request=delay:0.08"
# --scenario fleet: stretch the helper RTT so job throughput is
# RTT-bound — N replicas' worker pools then overlap N times the
# sleeping round trips and the served-rps scaling curve measures FLEET
# parallelism, not a 2-core host's CPU arithmetic
FLEET_RTT_SCHEDULE = "helper.request=delay:0.1"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _driver_cfg(
    path, db, health_port, ttl_s, cooldown_s, extra: str = "",
    cache_dir: str = "~/.cache/janus_tpu_xla",
):
    cfg = (
        f"database: {{url: {db}}}\n"
        f'health_check_listen_address: "127.0.0.1:{health_port}"\n'
        "jax_platform: cpu\n"
        f"compilation_cache_dir: {cache_dir}\n"
        "min_job_discovery_delay_secs: 0.1\n"
        "max_job_discovery_delay_secs: 0.5\n"
        f"worker_lease_duration_secs: {ttl_s}\n"
        "maximum_attempts_before_failure: 20\n"
        "outbound_circuit_breaker:\n"
        "  failure_threshold: 3\n"
        f"  open_cooldown_secs: {cooldown_s}\n"
        + extra
    )
    with open(path, "w") as f:
        f.write(cfg)
    return str(path)


def _spawn_driver(
    cfg_path, key, log_path, failpoints: str | None, extra_env=None,
    module: str = "janus_tpu.bin.aggregation_job_driver",
):
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        DATASTORE_KEYS=key,
        JAX_PLATFORMS="cpu",
    )
    # hermetic shape manifest per scenario run: a stale manifest
    # inherited from the developer/test environment would make every
    # driver boot pay an unrelated prewarm pass (scenarios that test
    # the prewarm itself pass an explicit path via extra_env)
    env["JANUS_SHAPE_MANIFEST"] = os.path.join(
        os.path.dirname(str(cfg_path)), "shape-manifest.jsonl"
    )
    env.update(extra_env or {})
    if failpoints:
        env["JANUS_FAILPOINTS"] = failpoints
    else:
        env.pop("JANUS_FAILPOINTS", None)
    logf = open(log_path, "wb")
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            module,
            "--config-file",
            str(cfg_path),
        ],
        env=env,
        stdout=logf,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


def _wait_healthz(port: int, deadline_s: float = 120.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                assert r.status == 200
                return
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)


def _scrape(port: int, path: str) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode()


def _metric_samples(text: str, name: str) -> dict[str, float]:
    """{label_block_or_'': value} for one family of a scraped /metrics
    page, via the shared exposition parser (janus_tpu.exposition — the
    same one scrape_check and the metrics tests use, incl. escaped
    label values)."""
    from janus_tpu.exposition import parse_exposition

    fam = parse_exposition(text)[0].get(name)
    if fam is None:
        return {}
    return {
        ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())): float(value)
        for sample_name, labels, value in fam.samples
        if sample_name == name
    }


def run_chaos(
    n_reports: int = 5,
    lease_ttl_s: int = 8,
    breaker_cooldown_s: float = 1.5,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Run the schedule; returns the invariant-assertion record. Every
    `*_ok` key must be True for the run to count as a pass."""
    from janus_tpu import failpoints
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import dataclasses

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-chaos-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    helper_db = os.path.join(tmp, "helper.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(helper_db, Crypter([key_bytes]), clock)

    result: dict = {"workdir": tmp, "schedule": "full" if full else "smoke"}
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")

        # pre-warm the engine programs into the persistent XLA cache:
        # the driver subprocesses (same single-device CPU config) load
        # them from disk instead of cold-compiling inside a short lease
        enable_compile_cache()
        warmup_engines(leader_ds)

        # --- phase 1: ground truth -------------------------------------
        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [(i % 3 != 0) * 1 for i in range(n_reports)]
        for m in measurements:
            client.upload(m)
        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )
        creator.run_once()
        result["admitted"] = len(measurements)
        result["ground_truth_sum"] = sum(measurements)

        def held_agg_leases():
            return [
                e
                for e in leader_ds.run_tx(
                    lambda tx: tx.get_held_lease_expiries(), "chaos_monitor"
                )
                if e[0] == "aggregation"
            ]

        def agg_jobs_by_state():
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "chaos_monitor"
            )
            return {
                state: n for (typ, state), n in counts.items() if typ == "aggregation"
            }

        # --- phase 2: crash between helper ack and leader commit --------
        from janus_tpu.failpoints import CRASH_EXIT_CODE

        ttl = int(lease_ttl_s)
        port_a = _free_port()
        cfg_a = _driver_cfg(
            os.path.join(tmp, "driver_a.yaml"), leader_db, port_a, ttl, breaker_cooldown_s
        )
        drv_a = _spawn_driver(
            cfg_a, key, os.path.join(tmp, "driver_a.log"), CRASH_SCHEDULE
        )
        procs.append(drv_a)
        rc_a = drv_a.wait(timeout=300)
        t_crash = time.monotonic()
        result["crash_exit_code"] = rc_a
        result["crash_ok"] = rc_a == CRASH_EXIT_CODE
        leases = held_agg_leases()
        # the dead driver's lease is still outstanding: nobody rolled it
        # back, exactly like SIGKILL
        result["lease_held_after_crash_ok"] = len(leases) == 1
        crashed_expiry = leases[0][3] if leases else 0
        states = agg_jobs_by_state()
        result["job_in_progress_after_crash_ok"] = states.get("in_progress", 0) >= 1

        # --- phase 3: restart into a helper storm -----------------------
        failpoints.configure(HELPER_5XX_SCHEDULE)  # helper-side real 500s
        port_b = _free_port()
        cfg_b = _driver_cfg(
            os.path.join(tmp, "driver_b.yaml"), leader_db, port_b, ttl, breaker_cooldown_s
        )
        drv_b = _spawn_driver(
            cfg_b, key, os.path.join(tmp, "driver_b.log"), STORM_SCHEDULE
        )
        procs.append(drv_b)
        _wait_healthz(port_b)
        # the recovery clock starts once a live driver exists: reacquire
        # latency must not be charged for driver B's own boot time
        t_recoverable = max(t_crash, time.monotonic())

        reacquired_at = None
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if reacquired_at is None:
                now_leases = held_agg_leases()
                if any(e[3] != crashed_expiry for e in now_leases):
                    reacquired_at = time.monotonic()
            states = agg_jobs_by_state()
            if states.get("in_progress", 0) == 0 and states.get("finished", 0) >= 1:
                if reacquired_at is None:
                    reacquired_at = time.monotonic()
                break
            time.sleep(0.05)
        states = agg_jobs_by_state()
        result["job_finished_ok"] = (
            states.get("finished", 0) >= 1 and states.get("in_progress", 0) == 0
        )
        result["lease_reacquire_s"] = (
            round(reacquired_at - t_recoverable, 3) if reacquired_at else None
        )
        # the crashed lease must be picked up within its TTL (plus
        # discovery latency margin): leases are always recovered
        result["lease_reacquired_within_ttl_ok"] = (
            reacquired_at is not None and (reacquired_at - t_recoverable) <= ttl + 3.0
        )
        failpoints.clear()

        # --- breaker cycle visibility (driver B is still alive) ---------
        metrics_text = _scrape(port_b, "/metrics")
        state_samples = _metric_samples(metrics_text, "janus_outbound_circuit_state")
        trans = _metric_samples(
            metrics_text, "janus_outbound_circuit_transitions_total"
        )
        result["circuit_state_samples"] = state_samples
        result["circuit_transitions"] = trans
        opened = sum(v for k, v in trans.items() if 'to="open"' in k)
        half = sum(v for k, v in trans.items() if 'to="half_open"' in k)
        closed = sum(v for k, v in trans.items() if 'to="closed"' in k)
        result["circuit_cycle_ok"] = (
            opened >= 1
            and half >= 1
            and closed >= 1
            and state_samples
            and all(v == 0.0 for v in state_samples.values())  # closed again
        )
        statusz = json.loads(_scrape(port_b, "/statusz"))
        result["statusz_circuit_ok"] = bool(
            statusz.get("outbound_circuit", {}).get("peers")
        )
        result["statusz_failpoints_armed_ok"] = (
            statusz.get("failpoints", {}).get("enabled") is True
        )
        step_backs = _metric_samples(metrics_text, "janus_job_step_back_total")
        result["step_backs"] = step_backs
        result["stepped_back_ok"] = (
            sum(v for k, v in step_backs.items() if "circuit_open" in k) >= 1
        )

        # --- SIGTERM drain of driver B ----------------------------------
        drv_b.send_signal(signal.SIGTERM)
        rc_b = drv_b.wait(timeout=60)
        log_b = open(os.path.join(tmp, "driver_b.log"), "rb").read()
        result["drain_ok"] = rc_b == 0 and b"shut down" in log_b

        # --- phase 4 (full): crash AFTER commit, before ack --------------
        if full:
            extra = [1] * max(3, n_reports // 2)
            for m in extra:
                client.upload(m)
            measurements += extra
            result["admitted"] = len(measurements)
            result["ground_truth_sum"] = sum(measurements)
            creator.run_once()
            port_c = _free_port()
            cfg_c = _driver_cfg(
                os.path.join(tmp, "driver_c.yaml"),
                leader_db,
                port_c,
                ttl,
                breaker_cooldown_s,
            )
            drv_c = _spawn_driver(
                cfg_c, key, os.path.join(tmp, "driver_c.log"), POST_COMMIT_CRASH_SCHEDULE
            )
            procs.append(drv_c)
            rc_c = drv_c.wait(timeout=300)
            result["post_commit_crash_ok"] = rc_c == CRASH_EXIT_CODE
            # death after the commit: the work IS durable; a clean
            # restart must find nothing left to redo (and the final
            # exact-count collection proves nothing was re-done)
            states = agg_jobs_by_state()
            result["post_commit_job_finished_ok"] = states.get("in_progress", 0) == 0
            port_d = _free_port()
            cfg_d = _driver_cfg(
                os.path.join(tmp, "driver_d.yaml"),
                leader_db,
                port_d,
                ttl,
                breaker_cooldown_s,
            )
            drv_d = _spawn_driver(
                cfg_d, key, os.path.join(tmp, "driver_d.log"), None
            )
            procs.append(drv_d)
            _wait_healthz(port_d)
            time.sleep(2.0)  # a couple of discovery passes
            drv_d.send_signal(signal.SIGTERM)
            rc_d = drv_d.wait(timeout=60)
            states = agg_jobs_by_state()
            result["clean_restart_ok"] = rc_d == 0 and states.get("in_progress", 0) == 0

        # --- phase 5: collect and compare against ground truth ----------
        import threading

        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig

            jd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                jd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=120.0)
            result["collected_count"] = collected.report_count
            result["collected_sum"] = collected.aggregate_result
            # THE invariant: exactly the admitted reports, no loss, no
            # double count — across a mid-commit crash, commit faults,
            # transport storms and helper 500s
            result["exactly_once_ok"] = (
                collected.report_count == len(measurements)
                and collected.aggregate_result == sum(measurements)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        leader_ds.close()
        helper_ds.close()


def _http_status(url: str, method: str = "GET", body: bytes | None = None,
                 headers: dict | None = None, timeout: float = 10.0):
    """(status, body bytes) tolerating non-2xx (urllib raises on those);
    the shared helper lives beside the HTTP client."""
    from janus_tpu.core.http_client import fetch_any_status

    return fetch_any_status(url, method=method, body=body, headers=headers, timeout=timeout)


def run_db_outage(
    n_warm: int = 4,
    outage_hold_s: float = 1.5,
    probe_interval_s: float = 0.15,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Datastore-outage survival schedule (see module docstring); every
    `*_ok` key must be True for the run to pass."""
    import threading

    from janus_tpu import failpoints
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import (
        HealthServer,
        enable_compile_cache,
        register_readiness_check,
        unregister_readiness_check,
        warmup_engines,
    )
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import (
        AggregationJobInitializeReq,
        Duration,
        Interval,
        Query,
        Role,
        Time,
    )
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import dataclasses

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-dbout-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    clock = RealClock()
    leader_ds = Datastore(
        os.path.join(tmp, "leader.sqlite"), Crypter([key_bytes]), clock
    )
    # the outage schedule targets ONLY this store (the in-process
    # helper's store keeps its default scope and stays up)
    leader_ds.failpoint_scope = "leader"
    helper_ds = Datastore(
        os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock
    )
    sup = leader_ds.start_supervision(
        probe_interval_s=probe_interval_s,
        down_threshold=2,
        reconnect_max_interval_s=max(1.0, 4 * probe_interval_s),
    )
    register_readiness_check("datastore", sup.readiness)

    result: dict = {
        "workdir": tmp,
        "schedule": "db_outage_full" if full else "db_outage_smoke",
    }
    leader_srv = helper_srv = health_srv = None
    leader_agg = None
    try:
        journal_dir = os.path.join(tmp, "upload-journal")
        leader_agg = Aggregator(
            leader_ds,
            clock,
            Config(
                collection_retry_after_s=1,
                upload_journal_path=journal_dir,
                upload_journal_replay_interval_s=0.2,
            ),
        )
        journal = leader_agg.upload_journal
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(DapHttpApp(leader_agg)).start()
        health_srv = HealthServer("127.0.0.1:0").start()
        hp = health_srv.port

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=201)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=2),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        enable_compile_cache()
        warmup_engines(leader_ds)

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id,
            leader_srv.url,
            helper_srv.url,
            leader_task.time_precision,
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)

        # --- sustained upload load: one background uploader running
        # across the whole schedule; every 201-acked measurement is
        # ground truth, wherever the ack came from -------------------
        acked: list[int] = []
        upload_errors: list[str] = []
        stop_uploader = threading.Event()

        def uploader():
            i = 0
            while not stop_uploader.is_set():
                m = (i % 3 != 0) * 1
                try:
                    client.upload(m)
                    acked.append(m)
                except Exception as e:  # shed/refused: NOT ground truth
                    upload_errors.append(f"{type(e).__name__}: {e}")
                i += 1
                stop_uploader.wait(0.04)

        # --- phase 1: healthy, journal armed but idle ----------------
        t0 = time.monotonic()
        for i in range(n_warm):
            client.upload(1)
            acked.append(1)
        result["healthy_upload_ms"] = round(
            (time.monotonic() - t0) / max(1, n_warm) * 1000, 2
        )
        # the armed-but-idle journal must not touch the hot path
        result["healthy_fsyncs"] = journal.fsyncs
        result["healthy_fsyncs_ok"] = journal.fsyncs == 0
        status, body = _http_status(f"http://127.0.0.1:{hp}/readyz")
        result["readyz_up_ok"] = (
            status == 200 and json.loads(body).get("ready") is True
        )
        # jobs created now but NOT stepped: the outage-window driver
        # pass below must park instead of burning their lease attempts
        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )
        creator.run_once()

        ut = threading.Thread(target=uploader, daemon=True)
        ut.start()
        time.sleep(6 * 0.04)  # a few sustained-load acks while healthy

        # --- phase 2: kill the datastore under load ------------------
        acked_before_outage = len(acked)
        failpoints.configure(DB_OUTAGE_SCHEDULE)
        deadline = time.monotonic() + 30
        while sup.state != "down" and time.monotonic() < deadline:
            time.sleep(0.02)
        result["supervisor_down_ok"] = sup.state == "down"
        status, body = _http_status(f"http://127.0.0.1:{hp}/readyz")
        try:
            reasons = json.loads(body).get("reasons", {})
        except Exception:
            reasons = {}
        result["readyz_down_status"] = status
        result["readyz_down_ok"] = status == 503 and bool(reasons)
        # aggregate-step routes shed 503 up front while the store is
        # down (the helper would only waste work on a doomed handler)
        tid = base64.urlsafe_b64encode(leader_task.task_id.data).decode().rstrip("=")
        jid = base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("=")
        status, _ = _http_status(
            f"{leader_srv.url}tasks/{tid}/aggregation_jobs/{jid}",
            method="PUT",
            body=b"x",
            headers={"Content-Type": AggregationJobInitializeReq.MEDIA_TYPE},
        )
        result["aggregate_shed_status"] = status
        result["aggregate_shed_ok"] = status == 503
        # a driver pass during the outage parks (no acquire, no lease
        # attempts burned) instead of crashing or marching to abandon
        drv = AggregationJobDriver(leader_ds, http)
        jd = JobDriver(
            JobDriverConfig(job_discovery_interval_s=0.1),
            drv.acquirer(60),
            drv.stepper,
        )
        result["driver_parked_ok"] = jd.run_once() == 0
        time.sleep(outage_hold_s)  # sustained load keeps acking into the journal
        depth_during = journal.depth()
        result["journal_depth_during_outage"] = depth_during[0]
        acked_during_outage = len(acked) - acked_before_outage
        result["acked_during_outage"] = acked_during_outage
        result["spilled_acked_ok"] = (
            acked_during_outage > 0 and depth_during[0] > 0
        )

        # --- phase 3: recovery ---------------------------------------
        failpoints.clear()
        deadline = time.monotonic() + 60
        while (
            sup.state != "up" or journal.depth()[0] > 0
        ) and time.monotonic() < deadline:
            time.sleep(0.05)
        result["supervisor_recovered_ok"] = sup.state == "up"
        result["journal_drained_ok"] = journal.depth()[0] == 0
        status, body = _http_status(f"http://127.0.0.1:{hp}/readyz")
        result["readyz_recovered_ok"] = (
            status == 200 and json.loads(body).get("ready") is True
        )
        time.sleep(6 * 0.04)  # a few more sustained-load acks while healthy
        stop_uploader.set()
        ut.join(timeout=30)
        result["admitted"] = len(acked)
        result["ground_truth_sum"] = sum(acked)
        result["upload_errors"] = upload_errors[:5]
        result["uploads_all_acked_ok"] = not upload_errors

        # --- phase 4: aggregate + collect == ground truth ------------
        creator.run_once()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            jd.run_once()
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "dbout_monitor"
            )
            agg = {s: n for (t, s), n in counts.items() if t == "aggregation"}
            if agg.get("in_progress", 0) == 0:
                break
            time.sleep(0.1)
        # absent key = zero jobs in that state (count_jobs_by_state only
        # returns states with rows)
        result["aggregation_done_ok"] = agg.get("in_progress", 0) == 0 and bool(
            agg.get("finished", 0)
        )

        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=120.0)
            result["collected_count"] = collected.report_count
            result["collected_sum"] = collected.aggregate_result
            # THE invariant: every 201 — healthy, spilled, replayed —
            # exactly once; no loss, no double count
            result["exactly_once_ok"] = (
                collected.report_count == len(acked)
                and collected.aggregate_result == sum(acked)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["journal_fsyncs_total"] = journal.fsyncs
        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        unregister_readiness_check("datastore")
        unregister_readiness_check("upload_journal")
        if leader_agg is not None:
            leader_agg.close()
        for srv in (leader_srv, helper_srv):
            if srv is not None:
                srv.stop()
        if health_srv is not None:
            health_srv.stop()
        leader_ds.close()
        helper_ds.close()


def run_device_hang(
    n_reports: int = 5,
    lease_ttl_s: int = 8,
    canary_delay_s: float = 1.5,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Deadline-aware device-path schedule (see module docstring):
    hung dispatch → watchdog abandon within the lease budget → engine
    quarantine → host-fallback serving → canary restore → exactly-once
    collection. Every `*_ok` key must be True to pass."""
    import threading

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import dataclasses

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-devhang-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock)

    result: dict = {
        "workdir": tmp,
        "schedule": "device_hang_full" if full else "device_hang_smoke",
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=202)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=3),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        enable_compile_cache()
        warmup_engines(leader_ds)

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [(i % 3 != 0) * 1 for i in range(n_reports)]
        for m in measurements:
            client.upload(m)
        AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        ).run_once()
        result["admitted"] = len(measurements)
        result["ground_truth_sum"] = sum(measurements)

        def held_agg_leases():
            return [
                e
                for e in leader_ds.run_tx(
                    lambda tx: tx.get_held_lease_expiries(), "devhang_monitor"
                )
                if e[0] == "aggregation"
            ]

        def agg_jobs_by_state():
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "devhang_monitor"
            )
            return {
                state: n for (typ, state), n in counts.items() if typ == "aggregation"
            }

        # --- spawn the real driver with the hang armed ------------------
        port = _free_port()
        cfg = _driver_cfg(
            os.path.join(tmp, "driver.yaml"), leader_db, port, int(lease_ttl_s), 1.5
        )
        drv = _spawn_driver(
            cfg,
            key,
            os.path.join(tmp, "driver.log"),
            DEVICE_HANG_SCHEDULE,
            extra_env={
                # fast canary cycle so the quarantine window is short but
                # still reliably observable by the 0.05s poll below
                "JANUS_CANARY_DELAY_S": str(canary_delay_s),
                "JANUS_CANARY_TIMEOUT_S": "30",
            },
        )
        procs.append(drv)
        _wait_healthz(port)

        # --- observe: lease bounded, watchdog + quarantine visible -----
        first_expiry = None
        released_at = None  # wall clock when the FIRST (hung) lease left
        quarantined_seen = False
        quarantined_at = None  # monotonic when quarantine first observed
        stalled_stack_seen = False
        abandoned_max = 0.0
        cap = None
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            leases = held_agg_leases()
            now_wall = clock.now().seconds
            if leases and first_expiry is None:
                first_expiry = leases[0][3]
                result["first_lease_expiry"] = first_expiry
            if (
                first_expiry is not None
                and released_at is None
                and not any(e[3] == first_expiry for e in leases)
            ):
                released_at = now_wall
            try:
                mtext = _scrape(port, "/metrics")
                backend = _metric_samples(mtext, "janus_engine_backend")
                if backend.get('state="quarantined",vdaf="count"') == 1.0:
                    if not quarantined_seen:
                        quarantined_at = time.monotonic()
                    quarantined_seen = True
                ab = _metric_samples(mtext, "janus_abandoned_dispatch_threads")
                abandoned_max = max(abandoned_max, *(ab.values() or [0.0]))
                statusz = json.loads(_scrape(port, "/statusz"))
                wd = statusz.get("device_watchdog", {})
                cap = wd.get("abandoned_thread_cap", cap)
                for ent in wd.get("stalled", []):
                    if ent.get("stack"):
                        stalled_stack_seen = True
            except Exception:
                pass  # scrape raced the driver's own work; retry next poll
            states = agg_jobs_by_state()
            if states.get("in_progress", 0) == 0 and states.get("finished", 0) >= 1:
                break
            time.sleep(0.05)

        states = agg_jobs_by_state()
        result["job_finished_ok"] = (
            states.get("finished", 0) >= 1 and states.get("in_progress", 0) == 0
        )
        # THE lease-bound invariant: the hung step released its lease
        # (stepped back) BEFORE the lease expired — the wedge never
        # outlives the lease and runs concurrently with a re-acquirer.
        # (+1s margin covers the 0.05s poll + second-granularity clock.)
        result["hung_lease_released_at"] = released_at
        result["lease_bounded_ok"] = (
            first_expiry is not None
            and released_at is not None
            and released_at <= first_expiry + 1
        )
        result["quarantined_observed_ok"] = quarantined_seen
        result["stalled_stack_ok"] = stalled_stack_seen
        result["abandoned_max"] = abandoned_max
        result["abandoned_under_cap_ok"] = (
            abandoned_max >= 1.0 and cap is not None and abandoned_max < cap
        )

        # --- wait for the canary to restore the device path (the job
        # usually finishes on host fallback BEFORE the canary's
        # cool-down elapses; the restore is observed live) ------------
        restore_deadline = time.monotonic() + 60
        restored_at = None
        mtext = _scrape(port, "/metrics")
        while time.monotonic() < restore_deadline:
            mtext = _scrape(port, "/metrics")
            quar = _metric_samples(mtext, "janus_engine_quarantines_total")
            if sum(v for k, v in quar.items() if 'event="restored"' in k) >= 1:
                restored_at = time.monotonic()
                break
            time.sleep(0.1)

        # warm canary restore (ISSUE 14): with the persistent compile
        # cache on (driver YAML) the canary's recompile+probe is a disk
        # load, so quarantine-open -> restored must be FAST — the
        # canary cool-down plus a bounded warm recompile, nothing like
        # the cold multi-minute rebuild this scenario used to tolerate.
        # 20s leaves CI headroom over the ~1.5s cool-down + warm probe.
        restore_elapsed = (
            None
            if quarantined_at is None or restored_at is None
            else restored_at - quarantined_at
        )
        result["restore_elapsed_s"] = (
            round(restore_elapsed, 2) if restore_elapsed is not None else None
        )
        result["restore_warm_ok"] = (
            restore_elapsed is not None and restore_elapsed <= 20.0
        )

        # --- steady state: restored to device, counters tell the story --
        hung = _metric_samples(mtext, "janus_hung_dispatches_total")
        result["hung_dispatches"] = hung
        result["hung_dispatch_ok"] = sum(hung.values()) >= 1
        step_backs = _metric_samples(mtext, "janus_job_step_back_total")
        result["step_backs"] = step_backs
        result["stepped_back_device_hang_ok"] = (
            sum(v for k, v in step_backs.items() if "device_hang" in k) >= 1
        )
        quar = _metric_samples(mtext, "janus_engine_quarantines_total")
        result["quarantine_events"] = quar
        result["quarantine_cycle_ok"] = (
            sum(v for k, v in quar.items() if 'event="open"' in k) >= 1
            and sum(v for k, v in quar.items() if 'event="restored"' in k) >= 1
        )
        backend = _metric_samples(mtext, "janus_engine_backend")
        result["restored_ok"] = (
            backend.get('state="device",vdaf="count"') == 1.0
            and backend.get('state="quarantined",vdaf="count"') == 0.0
        )
        statusz = json.loads(_scrape(port, "/statusz"))
        result["statusz_watchdog_ok"] = (
            statusz.get("device_watchdog", {}).get("hung_dispatches_total", 0) >= 1
        )

        # --- SIGTERM drain (release_hangs unparks the modeled wedge) ----
        drv.send_signal(signal.SIGTERM)
        rc = drv.wait(timeout=60)
        log_text = open(os.path.join(tmp, "driver.log"), "rb").read()
        result["drain_rc"] = rc
        result["drain_ok"] = rc == 0 and b"shut down" in log_text

        # --- collect and compare against ground truth -------------------
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=120.0)
            result["collected_count"] = collected.report_count
            result["collected_sum"] = collected.aggregate_result
            # interim work landed through the host fallback, restored
            # work on device — and every admitted report exactly once
            result["exactly_once_ok"] = (
                collected.report_count == len(measurements)
                and collected.aggregate_result == sum(measurements)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        leader_ds.close()
        helper_ds.close()


def run_cold_start(
    pairs: int = 1,
    full: bool = False,
    warm_budget_s: float = 10.0,
    workdir: str | None = None,
) -> dict:
    """Cold-start A/B (ISSUE 14): interleaved cold-cache vs warm-cache
    boots of the REAL driver binary, restart-to-first-dispatch measured
    via /debug/boot (phase sums proven exact by the boot-timeline
    tests). Both boots replay the SAME shape manifest through the AOT
    prewarm engine before /readyz flips ready — so ready means "every
    recorded specialization compiled", and the boot total IS the
    restart-to-first-dispatch number (the first real dispatch after
    ready runs an already-compiled program). The only difference
    between the two boots is the persistent XLA compile cache: empty
    (cold — every specialization pays trace + XLA compile) vs populated
    by the cold boot (warm — trace + disk load).

    Gates: warm restart-to-first-dispatch under `warm_budget_s` (the
    ROADMAP item 1 target: 10 s), warm at least 1.5x (smoke) / 3x
    (full) faster than cold, prewarm observed live on the warm boot
    (janus_engine_prewarm_total warmed > 0 AND statusz engine_prewarm
    cache hits > 0), and /debug/boot carrying the engine_warm_manifest
    sub-phase with ready only after the prewarm set compiled."""
    from janus_tpu.aggregator.shape_manifest import ShapeManifest
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Role
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-coldstart-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    db = os.path.join(tmp, "leader.sqlite")
    ds = Datastore(db, Crypter([key_bytes]), RealClock())
    result: dict = {"workdir": tmp, "schedule": "cold_start", "pairs": pairs}

    # two provisioned tasks with distinct circuits, so the manifest's
    # recorded geometry spans real production variety (count is the
    # cheap compile, histogram carries joint randomness and costs more
    # — its cold trace+compile is the 6-17 s/program class). The smoke
    # drops histogram to keep the tier-1 wall time bounded; the full
    # record (bench --mode served / standalone) measures both.
    insts = (
        (VdafInstance.count(), VdafInstance.histogram(length=4))
        if full
        else (VdafInstance.count(),)
    )
    for i, inst in enumerate(insts):
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), inst, Role.LEADER)
            .with_(
                collector_hpke_config=generate_hpke_config_and_private_key(
                    config_id=30 + i
                ).config,
            )
            .build()
        )
        ds.run_tx(lambda tx, t=task: tx.put_task(t), "provision")
    ds.close()

    # the manifest both boots replay: every (op, bucket) specialization
    # a serving driver observes on these two tasks — exactly what a
    # production restart finds on disk. Costs are descending so the
    # priority order is deterministic.
    def seed_manifest(path: str) -> int:
        man = ShapeManifest(path)
        n = 0
        for inst in insts:
            for b in (32, 64, 128):
                for op in ("leader_init", "helper_init", "aggregate"):
                    man.record(inst.to_dict(), op, b, (op, b), float(b) / 10, rows=b)
                    n += 1
            man.record(
                inst.to_dict(), "aggregate_pending", 64,
                ("aggregate_pending", 8, 64), 3.0, rows=64,
            )
            n += 1
        return n

    def one_boot(idx: int, label: str, cache_dir: str, manifest: str) -> dict:
        port = _free_port()
        cfg = _driver_cfg(
            os.path.join(tmp, f"driver-{idx}-{label}.yaml"),
            db,
            port,
            600,
            1.5,
            cache_dir=cache_dir,
            extra="engine:\n  prewarm_boot_budget_secs: 300\n",
        )
        drv = _spawn_driver(
            cfg,
            key,
            os.path.join(tmp, f"driver-{idx}-{label}.log"),
            None,
            extra_env={"JANUS_SHAPE_MANIFEST": manifest},
        )
        boot: dict = {"label": label}
        try:
            _wait_healthz(port, deadline_s=600.0)
            deadline = time.monotonic() + 60
            doc = {}
            while time.monotonic() < deadline:
                doc = json.loads(_scrape(port, "/debug/boot"))
                if doc.get("ready"):
                    break
                time.sleep(0.1)
            boot["ready_ok"] = bool(doc.get("ready"))
            boot["total_s"] = doc.get("total_s")
            boot["phases"] = {
                p["phase"]: p["seconds"] for p in doc.get("phases", [])
            }
            boot["manifest_phase_ok"] = "engine_warm_manifest" in boot["phases"]
            mtext = _scrape(port, "/metrics")
            pw = _metric_samples(mtext, "janus_engine_prewarm_total")
            boot["prewarm_total"] = pw
            boot["warmed"] = sum(
                v for k, v in pw.items() if 'outcome="warmed"' in k
            )
            statusz = json.loads(_scrape(port, "/statusz"))
            ep = statusz.get("engine_prewarm", {})
            boot["cache_hits"] = ep.get("prewarm", {}).get("cache_hits", 0)
            boot["cache_misses"] = ep.get("prewarm", {}).get("cache_misses", 0)
            boot["manifest_entries"] = ep.get("manifest", {}).get("entries", 0)
            boot["aot_loads"] = ep.get("aot", {}).get("loads", 0)
            boot["aot_saves"] = ep.get("aot", {}).get("saves", 0)
            drv.send_signal(signal.SIGTERM)
            boot["drain_rc"] = drv.wait(timeout=60)
        finally:
            if drv.poll() is None:
                drv.kill()
        return boot

    boots: list[dict] = []
    try:
        for i in range(pairs):
            cache_dir = os.path.join(tmp, f"xla-cache-{i}")
            manifest = os.path.join(tmp, f"shape-manifest-{i}.jsonl")
            result["manifest_seeded_entries"] = seed_manifest(manifest)
            # interleaved: cold then warm on the same (cache, manifest)
            # pair — the warm boot reads exactly what the cold one wrote
            boots.append(one_boot(i, "cold", cache_dir, manifest))
            boots.append(one_boot(i, "warm", cache_dir, manifest))
        result["boots"] = boots
        colds = [b for b in boots if b["label"] == "cold"]
        warms = [b for b in boots if b["label"] == "warm"]
        ok_shape = all(
            b.get("ready_ok") and b.get("total_s") is not None for b in boots
        )
        result["boots_ready_ok"] = ok_shape
        if ok_shape:
            cold_s = sorted(b["total_s"] for b in colds)[len(colds) // 2]
            warm_s = sorted(b["total_s"] for b in warms)[len(warms) // 2]
            result["cold_restart_to_first_dispatch_s"] = round(cold_s, 3)
            result["warm_restart_to_first_dispatch_s"] = round(warm_s, 3)
            result["speedup"] = round(cold_s / max(1e-9, warm_s), 2)
            # THE acceptance numbers (ISSUE 14 / ROADMAP item 1): warm
            # restart under 10 s, and >= 3x faster than cold (the full
            # record gate; the tier-1 smoke gates 1.5x so a CPU-starved
            # CI run cannot flake a real regression signal)
            result["warm_under_budget_ok"] = warm_s < warm_budget_s
            result["speedup_gate"] = 3.0 if full else 1.5
            result["speedup_ok"] = result["speedup"] >= result["speedup_gate"]
            result["manifest_phase_ok"] = all(
                b.get("manifest_phase_ok") for b in boots
            )
            result["prewarm_observed_ok"] = all(
                b.get("warmed", 0) >= result["manifest_seeded_entries"]
                for b in boots
            )
            result["warm_cache_hits_ok"] = all(
                b.get("cache_hits", 0) > 0 for b in warms
            )
            result["cold_cache_misses_ok"] = all(
                b.get("cache_misses", 0) > 0 for b in colds
            )
            # the AOT executable layer: cold boots SERIALIZE compiled
            # programs, warm boots LOAD them (no re-trace)
            result["cold_aot_saves_ok"] = all(
                b.get("aot_saves", 0) > 0 for b in colds
            )
            result["warm_aot_loads_ok"] = all(
                b.get("aot_loads", 0) > 0 for b in warms
            )
            result["drain_ok"] = all(b.get("drain_rc") == 0 for b in boots)
        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = bool(boots) and all(
            v for k, v in result.items() if k.endswith("_ok")
        )
        return result
    finally:
        # one_boot() kills any straggler in its own finally; the
        # workdir (sqlite, caches, logs) is kept for postmortems like
        # every other scenario's
        pass


def _histogram_counts(text: str, name: str) -> dict[str, float]:
    """{label_block: value} of a histogram family's _count samples."""
    from janus_tpu.exposition import parse_exposition

    fam = parse_exposition(text)[0].get(name)
    if fam is None:
        return {}
    return {
        ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())): float(value)
        for sample_name, labels, value in fam.samples
        if sample_name == name + "_count"
    }


def run_pipeline(
    n_reports: int = 24,
    job_size: int = 3,
    lease_ttl_s: int = 60,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Stage-pipeline overlap proof (ISSUE 9): the REAL driver binary —
    pipelined stepper enabled via its YAML `step_pipeline:` stanza —
    steps many small jobs against a loopback helper whose RTT is
    stretched by a `helper.request=delay` failpoint. Asserts the
    overlap actually happened (the device lane ran while an HTTP leg
    was in flight: janus_step_pipeline_overlap_total > 0 and a
    recorded overlap ratio > 0), every pipeline stage executed
    (stage-seconds counts for read/device/http/commit), the device-lane
    busy ratio is live, SIGTERM drains rc 0, and the final collection
    equals the admitted ground truth exactly — the pipeline never loses
    or double-steps a job. Every `*_ok` key must be True to pass."""
    import threading

    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-pipeline-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock)

    result: dict = {
        "workdir": tmp,
        "schedule": "pipeline_full" if full else "pipeline_smoke",
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=204)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=3),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        enable_compile_cache()
        warmup_engines(leader_ds, batch=job_size)

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [(i % 3 != 0) * 1 for i in range(n_reports)]
        for m in measurements:
            client.upload(m)
        # many SMALL jobs: the pipeline needs several concurrently
        # leased steps for its stages to interleave
        AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=job_size
            ),
        ).run_once()
        result["admitted"] = len(measurements)
        result["ground_truth_sum"] = sum(measurements)
        result["jobs_created"] = (n_reports + job_size - 1) // job_size

        def agg_jobs_by_state():
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "pipeline_monitor"
            )
            return {
                state: n for (typ, state), n in counts.items() if typ == "aggregation"
            }

        # --- spawn the real driver: pipelined stepper via YAML ----------
        port = _free_port()
        cfg = _driver_cfg(
            os.path.join(tmp, "driver.yaml"),
            leader_db,
            port,
            int(lease_ttl_s),
            1.5,
            extra=(
                "max_concurrent_job_workers: 4\n"
                "step_pipeline:\n"
                "  enabled: true\n"
                "  prefetch_depth: 2\n"
                "  http_inflight: 2\n"
                "  commit_inflight: 2\n"
            ),
        )
        drv = _spawn_driver(
            cfg, key, os.path.join(tmp, "driver.log"), PIPELINE_RTT_SCHEDULE
        )
        procs.append(drv)
        _wait_healthz(port)

        # --- wait for all jobs to finish, scraping the pipeline live ----
        deadline = time.monotonic() + 180
        mtext = ""
        while time.monotonic() < deadline:
            states = agg_jobs_by_state()
            if states.get("in_progress", 0) == 0 and states.get("finished", 0) >= result[
                "jobs_created"
            ]:
                break
            time.sleep(0.1)
        states = agg_jobs_by_state()
        result["job_states"] = states
        result["jobs_finished_ok"] = (
            states.get("finished", 0) >= result["jobs_created"]
            and states.get("in_progress", 0) == 0
        )

        mtext = _scrape(port, "/metrics")
        overlap = _metric_samples(mtext, "janus_step_pipeline_overlap_total")
        result["overlapped_dispatches"] = sum(overlap.values())
        result["overlap_ok"] = result["overlapped_dispatches"] >= 1
        busy = _metric_samples(mtext, "janus_device_lane_busy_ratio")
        result["device_lane_busy_ratio"] = max(busy.values() or [0.0])
        result["device_lane_busy_ok"] = result["device_lane_busy_ratio"] > 0
        stage_counts = _histogram_counts(mtext, "janus_step_pipeline_stage_seconds")
        result["stage_seconds_counts"] = stage_counts
        result["stages_executed_ok"] = all(
            any(f'stage="{s}"' in k and v > 0 for k, v in stage_counts.items())
            for s in ("read", "device", "http", "commit")
        )
        statusz = json.loads(_scrape(port, "/statusz"))
        sp = statusz.get("step_pipeline", {})
        result["statusz_overlap_ratio"] = sp.get("overlap_ratio", 0)
        result["statusz_overlap_events"] = sp.get("overlap_events", 0)
        result["statusz_pipeline_ok"] = (
            sp.get("jobs_done", 0) >= result["jobs_created"]
            and sp.get("overlap_events", 0) > 0
            and sp.get("device_lane", {}).get("concurrent_peak", 99) <= 1
        )

        # --- SIGTERM drain ---------------------------------------------
        drv.send_signal(signal.SIGTERM)
        rc = drv.wait(timeout=60)
        log_text = open(os.path.join(tmp, "driver.log"), "rb").read()
        result["drain_rc"] = rc
        result["drain_ok"] = rc == 0 and b"shut down" in log_text

        # --- collect and compare against ground truth -------------------
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=120.0)
            result["collected_count"] = collected.report_count
            result["collected_sum"] = collected.aggregate_result
            result["exactly_once_ok"] = (
                collected.report_count == len(measurements)
                and collected.aggregate_result == sum(measurements)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        leader_ds.close()
        helper_ds.close()


# --scenario resident: the first four SERVING device dispatches (two
# count tasks x leader_init + masked-delta) land clean, the FIFTH
# wedges forever — quarantining the engine while earlier jobs'
# aggregate state sits resident in device memory; two canary probes
# fail to hold the quarantine window open long enough to observe the
# flush live. The driver's boot warmup dispatches don't shift the
# anchor: warmup runs under failpoints.suppressed()
RESIDENT_SCHEDULE = "engine.dispatch=hang,count=1,after=4;engine.canary=error:1.0,count=2"


def run_resident(
    wave_sizes: tuple = (3, 3, 4, 3),
    lease_ttl_s: int = 6,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Resident aggregate state flush contract (docs/ARCHITECTURE.md
    "Resident aggregate state") against the REAL driver binary with
    `resident_accumulators` enabled and an 8-byte `resident_max_bytes`
    (one count slot). Deterministic schedule:

      1. two tasks (A, B) each land one job resident; task B's merge
         overflows the byte cap and LRU-EVICTS task A's slot through
         the flush path (reason="eviction") — observed live;
      2. task A's next job wedges on its device dispatch
         (engine.dispatch hang, after=4) → watchdog abandon →
         quarantine; the flusher's quarantine sweep writes task B's
         resident slot out (reason="quarantine") while the wedged job
         re-steps through the interim host engine;
      3. after the canary restores the device path, one more task-A
         job lands resident; SIGTERM drains it through the write-tx
         path (drain contract) and the final collections equal ALL
         tasks' admitted ground truths exactly — no share bytes lost
         across eviction, quarantine, or drain.

    A block-sparse sumvec task ("s", ISSUE 17) rides the same run: its
    first wave uploads inside the quarantine window (the sparse engine
    keeps dispatching while the count engine is wedged), its logical
    len-48 slot always overflows the 8-byte cap so every merge exits
    through the eviction flush, a second wave rides the restore->drain
    window, and its collection must equal the dense expansion of the
    admitted (block, values) pairs exactly — with the scatter row
    counter proving the gather/scatter kernel carried the deltas.

    wave_sizes: (task A wave 1, task B wave 1, task A hang wave,
    task A drain wave). Every `*_ok` key must be True to pass."""
    import threading

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import dataclasses

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-resident-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock)

    result: dict = {
        "workdir": tmp,
        "schedule": "resident_full" if full else "resident_smoke",
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()
        # ISSUE 17: a block-sparse task rides the same chaos phases as
        # the count tasks — its 768-byte slot always overflows the
        # 8-byte cap, so every merge exits through the eviction flush
        # path, and collection must still be exact
        sparse_vdaf = VdafInstance.sparse_sumvec(
            bits=3, length=48, block_size=4, max_blocks=3
        )
        tasks = {}
        for name, cfg_id, task_vdaf in (
            ("a", 210, vdaf),
            ("b", 211, vdaf),
            ("s", 212, sparse_vdaf),
        ):
            collector_kp = generate_hpke_config_and_private_key(config_id=cfg_id)
            leader_task = (
                TaskBuilder(QueryTypeConfig.time_interval(), task_vdaf, Role.LEADER)
                .with_(
                    leader_aggregator_endpoint=leader_srv.url,
                    helper_aggregator_endpoint=helper_srv.url,
                    collector_hpke_config=collector_kp.config,
                    aggregator_auth_token=AuthenticationToken.random_bearer(),
                    collector_auth_token=AuthenticationToken.random_bearer(),
                    min_batch_size=1,
                )
                .build()
            )
            helper_task = dataclasses.replace(
                leader_task,
                role=Role.HELPER,
                hpke_keys=(generate_hpke_config_and_private_key(config_id=4),),
            )
            leader_ds.run_tx(lambda tx, t=leader_task: tx.put_task(t), "provision")
            helper_ds.run_tx(lambda tx, t=helper_task: tx.put_task(t), "provision")
            tasks[name] = (leader_task, collector_kp, task_vdaf)
        # warm into the DRIVER's default persistent cache dir (NOT
        # enable_compile_cache's own default — a different path) so the
        # subprocess loads compiled programs from disk instead of
        # paying cold compiles against the lease watchdog: the sparse
        # leader_init compile alone (~15 s on CPU) would wedge past the
        # 6 s budget and spuriously quarantine the sparse engine
        enable_compile_cache(os.path.expanduser("~/.cache/janus_tpu_xla"))
        warmup_engines(leader_ds)

        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )
        truth = {"a": [], "b": [], "s": []}

        def upload(task_name: str, measurements) -> None:
            leader_task, _, task_vdaf = tasks[task_name]
            http = HttpClient()
            params = ClientParameters(
                leader_task.task_id, leader_srv.url, helper_srv.url,
                leader_task.time_precision,
            )
            client = Client.with_fetched_configs(params, task_vdaf, http, clock=clock)
            for m in measurements:
                client.upload(m)
            truth[task_name].extend(measurements)
            creator.run_once()

        def finished_jobs() -> int:
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "resident_monitor"
            )
            return sum(
                n
                for (typ, state), n in counts.items()
                if typ == "aggregation" and state == "finished"
            )

        def flush_samples(mtext: str) -> dict:
            return _metric_samples(mtext, "janus_engine_resident_flushes_total")

        # --- spawn the real driver: resident mode on, interval flush
        # effectively off (3600 s) so every flush observed below is an
        # EVICTION, QUARANTINE, or DRAIN flush — never the timer ------
        port = _free_port()
        cfg = _driver_cfg(
            os.path.join(tmp, "driver.yaml"),
            leader_db,
            port,
            int(lease_ttl_s),
            1.5,
            extra=(
                "resident_accumulators:\n"
                "  enabled: true\n"
                "  flush_interval_secs: 3600\n"
                "engine:\n"
                "  resident_max_bytes: 8\n"  # exactly ONE count slot
                # blocking engine warmup BEFORE the health listener: the
                # sparse leader_init/scatter compiles must not race the
                # lease watchdog mid-phase (the in-process warmup above
                # seeds the shared compile cache, so boot pays disk
                # loads, not cold compiles)
                "warmup_engines_at_boot: true\n"
            ),
        )
        drv = _spawn_driver(
            cfg,
            key,
            os.path.join(tmp, "driver.log"),
            RESIDENT_SCHEDULE,
            extra_env={
                "JANUS_CANARY_DELAY_S": "1.5",
                "JANUS_CANARY_TIMEOUT_S": "30",
            },
        )
        procs.append(drv)
        _wait_healthz(port)

        # --- phase 1: task A then task B land resident; B's merge
        # LRU-evicts A's slot through the flush path ------------------
        upload("a", [1, 0, 1][: wave_sizes[0]] or [1])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and finished_jobs() < 1:
            time.sleep(0.05)
        upload("b", [1, 1, 0][: wave_sizes[1]] or [1])
        eviction_seen = False
        while time.monotonic() < deadline and not eviction_seen:
            if finished_jobs() >= 2:
                samples = flush_samples(_scrape(port, "/metrics"))
                eviction_seen = (
                    samples.get('outcome="flushed",reason="eviction"', 0) >= 1
                )
            time.sleep(0.05)
        result["eviction_flush_ok"] = eviction_seen

        # --- phase 2: task A's next job wedges (hang armed after=4) ->
        # quarantine; the flusher sweep flushes B's slot live ---------
        upload("a", [1, 1, 1, 0][: wave_sizes[2]] or [1])
        quarantined_seen = False
        quarantine_flush_seen = False
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            try:
                mtext = _scrape(port, "/metrics")
            except Exception:
                time.sleep(0.1)
                continue
            backend = _metric_samples(mtext, "janus_engine_backend")
            if backend.get('state="quarantined",vdaf="count"') == 1.0:
                quarantined_seen = True
            samples = flush_samples(mtext)
            if samples.get('outcome="flushed",reason="quarantine"', 0) >= 1:
                quarantine_flush_seen = True
            if quarantine_flush_seen and finished_jobs() >= 3:
                break
            time.sleep(0.05)
        result["quarantined_observed_ok"] = quarantined_seen
        result["quarantine_flush_ok"] = quarantine_flush_seen
        step_backs = _metric_samples(
            _scrape(port, "/metrics"), "janus_job_step_back_total"
        )
        result["stepped_back_device_hang_ok"] = (
            sum(v for k, v in step_backs.items() if "device_hang" in k) >= 1
        )

        # --- sparse wave 1: uploaded inside the quarantine window (the
        # count engine is still wedged; the sparse engine dispatches on
        # its own device path).  Its 768-byte slot overflows the 8-byte
        # cap at merge time, so the state exits through the EVICTION
        # flush — observed via the flush counter delta plus the scatter
        # row counter proving the gather/scatter kernel ran (ISSUE 17)
        pre_sparse_evictions = flush_samples(_scrape(port, "/metrics")).get(
            'outcome="flushed",reason="eviction"', 0
        )
        upload(
            "s",
            [
                [(0, [1, 2, 3, 4]), (5, [7, 0, 1, 2])],
                [(0, [0, 1, 0, 1]), (3, [2, 2, 2, 2]), (11, [5, 0, 0, 6])],
            ],
        )
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and finished_jobs() < 4:
            time.sleep(0.05)

        # --- phase 3: canary restores the device path; one more job
        # lands resident and SIGTERM drains it ------------------------
        restore_deadline = time.monotonic() + 90
        while time.monotonic() < restore_deadline:
            backend = _metric_samples(
                _scrape(port, "/metrics"), "janus_engine_backend"
            )
            if backend.get('state="device",vdaf="count"') == 1.0:
                break
            time.sleep(0.1)
        result["restored_ok"] = backend.get('state="device",vdaf="count"') == 1.0
        # sparse wave 2 rides the restore->drain window; it merges (and
        # self-evicts through the flush path) BEFORE task A's final job
        # lands resident, so the LRU sweep cannot evict A's slot and
        # the drain contract below stays deterministic
        upload("s", [[(2, [1, 0, 0, 3]), (7, [0, 4, 0, 0])]])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and finished_jobs() < 5:
            time.sleep(0.05)
        upload("a", [0, 1, 1][: wave_sizes[3]] or [1])
        resident_before_drain = 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if finished_jobs() >= 6:
                statusz = json.loads(_scrape(port, "/statusz"))
                ra = statusz.get("resident_accumulators", {})
                resident_before_drain = sum(
                    e.get("buffers", 0) for e in ra.get("engines", [])
                )
                if resident_before_drain >= 1:
                    result["statusz_resident_bytes"] = ra.get("total_bytes")
                    break
            time.sleep(0.05)
        result["resident_before_drain_ok"] = resident_before_drain >= 1

        mtext = _scrape(port, "/metrics")
        samples = flush_samples(mtext)
        result["flush_samples"] = samples
        result["no_lost_flushes_ok"] = not any(
            'outcome="lost"' in k and v > 0 for k, v in samples.items()
        )
        # sparse ride-along (ISSUE 17), judged cumulatively before the
        # drain: the count choreography contributes exactly ONE
        # eviction flush, so any excess over the pre-sparse count is
        # the sparse slot exiting through the eviction path, and the
        # scatter row counter proves the gather/scatter kernel (not a
        # dense or host detour) carried the sparse deltas
        scatter_samples = _metric_samples(
            mtext, "janus_engine_scatter_rows_total"
        )
        result["sparse_scatter_rows"] = sum(scatter_samples.values())
        result["sparse_scatter_observed_ok"] = (
            scatter_samples.get('vdaf="sparse_sumvec"', 0) > 0
        )
        result["sparse_eviction_flush_ok"] = (
            samples.get('outcome="flushed",reason="eviction"', 0)
            > pre_sparse_evictions
        )
        hd = _metric_samples(mtext, "janus_engine_hd_bytes_total")
        result["hd_bytes"] = hd
        result["hd_bytes_ok"] = (
            sum(v for k, v in hd.items() if 'direction="h2d"' in k) > 0
        )

        # --- SIGTERM drain: the resident remainder flushes through the
        # write-tx path before exit (collection proves it landed) -----
        drv.send_signal(signal.SIGTERM)
        rc = drv.wait(timeout=60)
        log_text = open(os.path.join(tmp, "driver.log"), "rb").read()
        result["drain_rc"] = rc
        result["drain_ok"] = rc == 0 and b"shut down" in log_text

        # --- collect BOTH tasks and compare against ground truth -----
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            for name in ("a", "b", "s"):
                leader_task, collector_kp, task_vdaf = tasks[name]
                collector = Collector(
                    CollectorParameters(
                        leader_task.task_id,
                        leader_srv.url,
                        leader_task.collector_auth_token,
                        collector_kp,
                    ),
                    task_vdaf,
                    HttpClient(),
                )
                tp = leader_task.time_precision
                start = clock.now().to_batch_interval_start(tp)
                query = Query.time_interval(
                    Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
                )
                collected = collector.collect(query, timeout_s=120.0)
                if name == "s":
                    # ground truth at the LOGICAL length: expand every
                    # (block, values) pair onto the dense vector
                    want = [0] * sparse_vdaf.length
                    for m in truth["s"]:
                        for blk, vals in m:
                            for j, v in enumerate(vals):
                                want[blk * sparse_vdaf.block_size + j] += v
                    got = list(collected.aggregate_result)
                else:
                    want = sum(truth[name])
                    got = collected.aggregate_result
                result[f"collected_count_{name}"] = collected.report_count
                result[f"collected_sum_{name}"] = got
                result[f"exactly_once_{name}_ok"] = (
                    collected.report_count == len(truth[name]) and got == want
                )
                result[f"admitted_{name}"] = len(truth[name])
                result[f"ground_truth_sum_{name}"] = want
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        leader_ds.close()
        helper_ds.close()


def claim_roundtrip_stats(n_jobs: int = 32, batch: int = 16) -> dict:
    """Claim round-trips per job, measured not assumed (ISSUE 15): the
    batched claim transaction vs a reimplementation of the old per-row
    loop, both over the recorded-conversation pg_fake driver so every
    statement is counted exactly as it would hit the PG wire. The
    batched form issues ONE statement per claim transaction; the
    per-row loop issued 1 SELECT + K guarded UPDATEs."""
    import secrets as _secrets

    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.models import AggregationJobModel, AggregationJobState
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import AggregationJobId, Duration, Interval, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    def seeded_store():
        eph = EphemeralDatastore(clock=MockClock(Time(1_600_000_000)), engine="pgfake")
        ds = eph.datastore
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
            .with_(min_batch_size=1)
            .build()
        )
        ds.run_tx(lambda tx: tx.put_task(task))

        def put_jobs(tx):
            for i in range(n_jobs):
                tx.put_aggregation_job(
                    AggregationJobModel(
                        task.task_id,
                        AggregationJobId(i.to_bytes(16, "big")),
                        b"",
                        b"\x01",
                        Interval(Time(1_600_000_000), Duration(1)),
                        AggregationJobState.IN_PROGRESS,
                        0,
                    )
                )

        ds.run_tx(put_jobs)
        return eph, ds

    def count_statements(ds, claim_fn) -> tuple[int, int]:
        """(statements executed, jobs claimed) draining the store."""
        driver = ds._driver
        driver.clear_log()
        claimed = 0
        while True:
            got = ds.run_tx(lambda tx: claim_fn(tx))
            if not got:
                break
            claimed += len(got)
        return len(driver.statements("execute")), claimed

    def legacy_per_row(tx):
        """The pre-ISSUE-15 per-row claim loop, preserved here as the
        measurement oracle (one SELECT, then a guarded UPDATE ..
        RETURNING per candidate row)."""
        now = tx._clock.now().seconds
        rows = tx._c.execute(
            "SELECT task_id, job_id FROM aggregation_jobs"
            " WHERE state = 'in_progress' AND lease_expiry <= ?"
            " ORDER BY lease_expiry LIMIT ?" + tx._lease_suffix,
            (now, batch),
        ).fetchall()
        out = []
        for task_id, job_id in rows:
            token = _secrets.token_bytes(16)
            cur = tx._c.execute(
                "UPDATE aggregation_jobs SET lease_expiry = ?, lease_token = ?,"
                " lease_attempts = lease_attempts + 1"
                " WHERE task_id = ? AND job_id = ? AND state = 'in_progress'"
                " AND lease_expiry <= ? RETURNING lease_attempts",
                (now + 600, token, task_id, job_id, now),
            ).fetchone()
            if cur is not None:
                out.append((task_id, job_id))
        return out

    eph, ds = seeded_store()
    try:
        batched_stmts, batched_claimed = count_statements(
            ds,
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), batch),
        )
    finally:
        eph.cleanup()
    eph, ds = seeded_store()
    try:
        legacy_stmts, legacy_claimed = count_statements(ds, legacy_per_row)
    finally:
        eph.cleanup()
    batched_per_job = batched_stmts / max(1, batched_claimed)
    legacy_per_job = legacy_stmts / max(1, legacy_claimed)
    return {
        "jobs": n_jobs,
        "claim_batch": batch,
        "batched_statements": batched_stmts,
        "batched_claimed": batched_claimed,
        "batched_stmts_per_job": round(batched_per_job, 3),
        "per_row_statements": legacy_stmts,
        "per_row_claimed": legacy_claimed,
        "per_row_stmts_per_job": round(legacy_per_job, 3),
        # THE acceptance comparison: claim round-trips per job,
        # batched vs the per-row loop (gate: measurably below)
        "roundtrip_ratio": round(legacy_per_job / max(1e-9, batched_per_job), 1),
        "claim_roundtrips_ok": (
            batched_claimed == n_jobs
            and legacy_claimed == n_jobs
            and batched_per_job < legacy_per_job / 2
        ),
    }


def run_fleet(
    replicas: int = 4,
    jobs_per_replica: int = 24,
    job_size: int = 2,
    lease_ttl_s: int = 5,
    steal_after_s: int = 2,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Fleet-grade scale-out proof (ISSUE 15; docs/ARCHITECTURE.md
    "Running a fleet"): N REAL aggregation-job-driver binaries — each
    with its own fleet identity and shard slice — over ONE leader
    datastore, under RTT-bound load. Phases:

      1. claim-efficiency: batched claim tx vs the old per-row loop,
         statements counted on the recorded PG wire (in-process);
      2. scaling curve: served rps with 1, 2 and 4 replicas (2 in the
         smoke), each phase its own driver set + fresh job wave — the
         BENCH `fleet_scaling` record;
      3. chaos: a full fleet under load — SIGKILL one replica while it
         HOLDS leases (lease expires, survivors steal its shard after
         the delay, attempt accounting intact), SIGTERM-drain another
         (leases handed back immediately, rc 0), restart the killed
         replica (warm-boot path) and prove it serves a fresh wave;
      4. collection == admitted ground truth EXACTLY across every
         wave, zero lease-token conflicts on every scraped replica
         (no job double-stepped), and no job starves past
         ttl + steal + margin after the kill.

    Every `*_ok` key must be True to pass."""
    import threading

    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore, replica_holder_tag
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-fleet-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock)

    result: dict = {
        "workdir": tmp,
        "schedule": "fleet_full" if full else "fleet_smoke",
        "replicas": replicas,
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    # report-flow conservation gate (ISSUE 20): the ledger evaluates
    # against the shared leader store at every quiesce point — the
    # books must close (imbalance 0) after every wave, through the
    # kill, the drain, the steal and the restart. grace 0: a nonzero
    # residual at a quiesce point breaches immediately. The installed
    # evaluator also powers the in-process collection driver's
    # cross-aggregator reconciliation in phase 4.
    from janus_tpu import ledger as ledger_mod

    ledger_ev = ledger_mod.install_ledger(
        leader_ds, ledger_mod.LedgerConfig(grace_s=0.0)
    )
    conservation: dict[str, dict] = {}

    def conservation_check(tag: str) -> bool:
        doc = ledger_ev.evaluate_once()
        imb = {
            label: dict(t["imbalance"]) for label, t in doc.get("tasks", {}).items()
        }
        conservation[tag] = imb
        return bool(imb) and all(
            v.get("ingest") == 0 and v.get("collect") == 0 for v in imb.values()
        )

    try:
        # --- phase 1: claim round-trips per job, measured ------------
        result["claim_stats"] = claim_roundtrip_stats()
        result["claim_roundtrips_ok"] = result["claim_stats"]["claim_roundtrips_ok"]

        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=205)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=5),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        enable_compile_cache()
        warmup_engines(leader_ds, batch=job_size)

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=job_size
            ),
        )
        measurements: list[int] = []
        finished_target = {"jobs": 0}

        def upload_wave(n_reports: int) -> int:
            wave = [(i % 3 != 0) * 1 for i in range(n_reports)]
            for m in wave:
                client.upload(m)
            measurements.extend(wave)
            return (n_reports + job_size - 1) // job_size

        def finished_jobs() -> int:
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "fleet_monitor"
            )
            return sum(
                n
                for (typ, state), n in counts.items()
                if typ == "aggregation" and state == "finished"
            )

        def wait_finished(deadline_s: float) -> bool:
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if finished_jobs() >= finished_target["jobs"]:
                    return True
                time.sleep(0.05)
            return finished_jobs() >= finished_target["jobs"]

        def spawn_replica(i: int, shard_count: int, tag: str):
            """One REAL driver binary with fleet identity replica-i of
            shard_count; `tag` keeps per-phase artifacts apart."""
            port = _free_port()
            cfg = _driver_cfg(
                os.path.join(tmp, f"driver-{tag}-{i}.yaml"),
                leader_db,
                port,
                int(lease_ttl_s),
                1.5,
                extra=(
                    "max_concurrent_job_workers: 4\n"
                    "fleet:\n"
                    f"  replica_id: replica-{i}\n"
                    f"  shard_count: {shard_count}\n"
                    f"  shard_index: {i}\n"
                    f"  steal_after_secs: {steal_after_s}\n"
                ),
            )
            drv = _spawn_driver(
                cfg, key, os.path.join(tmp, f"driver-{tag}-{i}.log"), FLEET_RTT_SCHEDULE
            )
            procs.append(drv)
            return i, port, drv

        def drain(replica_set, expect_rc0: bool = True) -> bool:
            ok = True
            for _i, _port, drv in replica_set:
                if drv.poll() is None:
                    drv.send_signal(signal.SIGTERM)
            for _i, _port, drv in replica_set:
                try:
                    rc = drv.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    drv.kill()
                    rc = None
                ok = ok and (rc == 0 or not expect_rc0)
            return ok

        # --- phase 2: served-rps scaling curve -----------------------
        phase_counts = (1, 2, 4) if full else (1, 2)
        rps: dict[int, float] = {}
        for n in phase_counts:
            fleet = [spawn_replica(i, n, f"scale{n}") for i in range(n)]
            for _i, port, _drv in fleet:
                _wait_healthz(port)
            jobs = upload_wave(jobs_per_replica * n * job_size)
            finished_target["jobs"] += jobs
            t0 = time.monotonic()
            creator.run_once()
            done = wait_finished(120)
            elapsed = time.monotonic() - t0
            result[f"scale_{n}_done_ok"] = done
            rps[n] = (jobs_per_replica * n * job_size) / max(1e-9, elapsed)
            result[f"drain_scale_{n}_ok"] = drain(fleet)
            # quiesce point: the wave is finished and the replicas are
            # drained — every admitted report must be accounted for
            result[f"conservation_scale_{n}_ok"] = conservation_check(f"scale_{n}")
        n_max = max(phase_counts)
        result["fleet_scaling"] = {
            "replica_counts": list(phase_counts),
            "served_rps": {str(n): round(rps[n], 1) for n in phase_counts},
            "speedup_max_vs_1": round(rps[n_max] / max(1e-9, rps[1]), 2),
            "scaling_efficiency": round(
                rps[n_max] / max(1e-9, rps[1]) / n_max, 2
            ),
            "claim_stats": result["claim_stats"],
        }
        # CI-honest gate: RTT-bound work must scale meaningfully with
        # replica count (full 1->4: >= 1.8x; smoke 1->2: >= 1.2x) — the
        # record carries the real efficiency number either way
        gate = 1.8 if full else 1.2
        result["scaling_gate"] = gate
        result["scaling_ok"] = result["fleet_scaling"]["speedup_max_vs_1"] >= gate

        # --- phase 3: kill / drain / restart under load --------------
        chaos_n = replicas if full else 2
        fleet = [spawn_replica(i, chaos_n, "chaos") for i in range(chaos_n)]
        by_idx = {i: (i, port, drv) for i, port, drv in fleet}
        for _i, port, _drv in fleet:
            _wait_healthz(port)
        jobs = upload_wave(jobs_per_replica * chaos_n * job_size)
        finished_target["jobs"] += jobs
        creator.run_once()

        # wait until the victim (replica 0) HOLDS a lease mid-step,
        # proven by the provenance tag on the held row. If a wave
        # drains before the poll catches it (a fast machine, not a
        # product defect), upload ANOTHER wave and keep looking — the
        # kill must be provably mid-step, never a guess.
        victim_tag = replica_holder_tag("replica-0").hex()
        tags = {replica_holder_tag(f"replica-{i}").hex(): i for i in range(chaos_n)}
        victim_holding = False
        seen_holder_tags: set = set()
        for _attempt in range(4):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                holders = leader_ds.run_tx(
                    lambda tx: tx.get_lease_holders(), "fleet_monitor"
                )
                seen_holder_tags.update(h[3] for h in holders)
                if any(h[3] == victim_tag for h in holders):
                    victim_holding = True
                    break
                if finished_jobs() >= finished_target["jobs"]:
                    break  # wave drained before we caught the victim
                time.sleep(0.01)
            if victim_holding:
                break
            finished_target["jobs"] += upload_wave(jobs_per_replica * chaos_n * job_size)
            creator.run_once()
        result["victim_held_lease_ok"] = victim_holding
        result["holder_tags_are_replica_tags_ok"] = bool(seen_holder_tags) and all(
            t in tags for t in seen_holder_tags
        )

        # SIGKILL the victim MID-STEP: nothing releases its leases —
        # they must expire and drain through TTL + steal-after
        _, victim_port, victim = by_idx[0]
        victim.send_signal(signal.SIGKILL)
        t_kill = time.monotonic()
        result["victim_killed_rc"] = victim.wait(timeout=30)
        result["victim_sigkill_ok"] = result["victim_killed_rc"] == -signal.SIGKILL

        # SIGTERM-drain another replica: clean rc 0, leases handed back
        drain_idx = 1
        result["drain_mid_load_ok"] = drain([by_idx[drain_idx]])

        # survivors (or nobody, in the 2-replica smoke: the restarted
        # victim) must finish the wave; no job starves past the bound
        survivors = [by_idx[i] for i in range(chaos_n) if i not in (0, drain_idx)]

        # restart the killed replica (same identity + shard; warm-boot
        # path: shared compile cache + shape manifest)
        restarted = spawn_replica(0, chaos_n, "restart")
        _wait_healthz(restarted[1])
        result["restart_boot_ok"] = True
        survivors.append(restarted)

        starvation_bound_s = lease_ttl_s + steal_after_s + 45
        done = wait_finished(starvation_bound_s)
        result["chaos_wave_done_ok"] = done
        result["post_kill_drain_s"] = round(time.monotonic() - t_kill, 1)
        result["no_starvation_ok"] = (
            done and result["post_kill_drain_s"] <= starvation_bound_s
        )

        # a fresh wave lands with the restarted replica participating
        jobs = upload_wave(jobs_per_replica * job_size)
        finished_target["jobs"] += jobs
        creator.run_once()
        result["restart_wave_done_ok"] = wait_finished(60)

        # fleet observability on every live replica: replica_info
        # carries the configured identity, the batched claim metrics
        # are live, and the lease-conflict counter reads ZERO — no job
        # was ever double-stepped
        conflicts = 0.0
        acquired_jobs = 0.0
        claim_txs = 0.0
        steals = 0.0
        replica_info_ok = True
        mesh_statusz_ok = True
        for i, port, _drv in survivors:
            mtext = _scrape(port, "/metrics")
            info = _metric_samples(mtext, "janus_replica_info")
            want = f'replica_id="replica-{i}"'
            if not any(want in k and v == 1.0 for k, v in info.items()):
                replica_info_ok = False
            conflicts += sum(
                _metric_samples(mtext, "janus_lease_conflicts_total").values()
            )
            acquired_jobs += sum(
                _metric_samples(mtext, "janus_lease_acquired_jobs_total").values()
            )
            claim_txs += sum(
                v
                for k, v in _metric_samples(
                    mtext, "janus_lease_acquire_tx_total"
                ).items()
                if 'outcome="claimed"' in k
            )
            steals += sum(
                _metric_samples(mtext, "janus_lease_steals_total").values()
            )
            statusz = json.loads(_scrape(port, "/statusz"))
            if statusz.get("fleet", {}).get("replica_id") != f"replica-{i}":
                replica_info_ok = False
            # every replica — including the restart that replaced the
            # killed one — must publish the mesh dispatch section (the
            # single-controller lane is per-process state; a restart
            # that lost it would dispatch mesh programs unserialized)
            mesh = statusz.get("mesh")
            if not (isinstance(mesh, dict) and isinstance(mesh.get("queue"), dict)):
                mesh_statusz_ok = False
        result["replica_info_ok"] = replica_info_ok
        result["mesh_statusz_ok"] = mesh_statusz_ok
        result["lease_conflicts_total"] = conflicts
        result["zero_lease_conflicts_ok"] = conflicts == 0.0
        result["fleet_acquired_jobs"] = acquired_jobs
        result["fleet_claim_txs"] = claim_txs
        result["batched_claims_ok"] = (
            claim_txs > 0 and acquired_jobs / max(1.0, claim_txs) > 1.0
        )
        result["lease_steals"] = steals
        result["steals_observed_ok"] = steals >= 1.0  # the dead shard drained

        result["drain_final_ok"] = drain(survivors)
        # quiesce point: kill + drain + steal + restart are behind us
        # and every wave is finished — the books must still close
        result["conservation_chaos_ok"] = conservation_check("chaos")

        # --- phase 4: collect EVERYTHING vs ground truth -------------
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=180.0)
            result["admitted"] = len(measurements)
            result["ground_truth_sum"] = sum(measurements)
            result["collected_count"] = collected.report_count
            result["collected_sum"] = collected.aggregate_result
            # THE invariant: every admitted report exactly once across
            # kill, drain, steal, and restart — no loss, no double
            result["exactly_once_ok"] = (
                collected.report_count == len(measurements)
                and collected.aggregate_result == sum(measurements)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        # quiesce point: post-collection BOTH stages must close —
        # ingest (admitted == aggregated) and collect (aggregated ==
        # collected, nothing left awaiting)
        result["conservation_collected_ok"] = conservation_check("collected")
        result["conservation"] = conservation
        # cross-aggregator reconciliation ran inside the collection
        # driver's step (the installed evaluator + the helper's
        # authenticated /tasks/{id}/ledger endpoint): on this clean
        # lane the per-batch counts must AGREE — divergence 0
        from janus_tpu.metrics import task_id_label

        label = task_id_label(leader_task.task_id.data)
        peer = ledger_ev.document().get("tasks", {}).get(label, {}).get("peer")
        result["peer_reconciliation"] = peer
        result["peer_reconciled_ok"] = (
            peer is not None and peer.get("divergence") == 0
        )

        # --- phase 5: injected-loss lane -----------------------------
        # the ledger.drop_report failpoint silently deletes ONE
        # admitted report AFTER its admission tx counted it — the
        # tamper no throughput metric can see. The next ledger
        # evaluation (one sampler tick) must book a +1 ingest
        # imbalance, breach immediately (grace 0), and turn the
        # `conservation` SLO signal bad.
        from janus_tpu import failpoints as failpoints_inproc
        from janus_tpu.slo import ConservationSignal

        class _SigState:
            _condition_state: dict = {}

        sig_engine = _SigState()
        sig = ConservationSignal()
        slo_bad_before, _, _ = sig.read(sig_engine)
        failpoints_inproc.configure("ledger.drop_report=error:1.0,count=1")
        try:
            client.upload(1)
        finally:
            failpoints_inproc.clear()
        loss_doc = ledger_ev.evaluate_once()
        loss_imb = loss_doc.get("tasks", {}).get(label, {}).get("imbalance", {})
        slo_bad_after, _, _ = sig.read(sig_engine)
        result["loss_injected_imbalance"] = loss_imb.get("ingest")
        result["loss_breaches"] = list(loss_doc.get("breaches", []))
        result["loss_detected_ok"] = (
            loss_imb.get("ingest") == 1
            and any(s.endswith("/ingest") for s in loss_doc.get("breaches", []))
            and slo_bad_after > slo_bad_before
        )

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        ledger_mod.uninstall_ledger()
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        leader_ds.close()
        helper_ds.close()


def run_soak(
    epochs: int = 4,
    reports_per_epoch: int = 8,
    job_size: int = 4,
    report_expiry_s: float = 30.0,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Endurance soak (ISSUE 18; docs/OBSERVABILITY.md "Flight recorder
    and trend alerts"): sustained open-loop load with TIME-INTERVAL TASK
    CHURN and GC actually deleting collected rows, judged by the flight
    recorder's trend verdicts instead of a single end-state snapshot.

      - one epoch = a fresh time-interval task (short report_expiry_age)
        + an upload wave with known ground truth + aggregation by two
        REAL driver binaries + an EXACT collection of that epoch + a GC
        pass (old epochs' rows are expired by then and really deleted);
      - driver A runs clean: its /debug/flight analysis must call
        rss_bytes and datastore_rows FLAT over the trailing window (no
        leak-gated series leaking), p99 families stable, recorder
        self-overhead <= 1%, ring inside its byte budget, statusz
        `flight` section fresh;
      - driver B runs with the flight.synthetic_leak failpoint armed:
        the injected leak must flip janus_flight_leak_active, land the
        series in analysis.leaking, and fire the resource_trend SLO
        alert on /alertz within the window_scale-shrunk ladder.

    The smoke runs on sqlite in tier-1 minutes; the full run targets
    PostgreSQL when JANUS_TEST_DATABASE_URL points at the server from
    docker-compose.pg.yaml (falls back to sqlite otherwise). Every
    `*_ok` key must be True to pass."""
    import threading

    import dataclasses

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.garbage_collector import GarbageCollector
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, open_datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-soak-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    # the full run soaks the real PostgreSQL datastore when the
    # docker-compose.pg.yaml server is up (JANUS_TEST_DATABASE_URL);
    # the smoke — and a full run without the server — uses sqlite
    pg_url = os.environ.get("JANUS_TEST_DATABASE_URL") if full else None
    leader_db = pg_url or os.path.join(tmp, "leader.sqlite")
    leader_ds = open_datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = open_datastore(
        os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock
    )

    # flight/SLO cadences: production-shaped in the full run, shrunk to
    # tier-1 seconds in the smoke (window_scale turns the 1h/5m page
    # ladder into 36s/3s — the injected leak fires the trend page in
    # seconds instead of an hour)
    flight_interval_s = 2.0 if full else 0.5
    # a TRAILING window: long enough for robust slopes, short enough
    # that by verdict time it covers steady state instead of the boot
    # ramp (a window spanning the whole run would honestly — and
    # uselessly — report "rows grew" for the fill phase)
    flight_window_s = 600.0 if full else 15.0
    window_scale = 0.1 if full else 0.01

    def soak_extra(flight_dir: str) -> str:
        return (
            "max_concurrent_job_workers: 4\n"
            "health_sampler_interval_secs: 0.5\n"
            "flight:\n"
            f"  dir: {flight_dir}\n"
            f"  interval_secs: {flight_interval_s}\n"
            "  analyze_every: 3\n"
            f"  window_secs: {flight_window_s}\n"
            "  min_points: 10\n"
            "  rollup_secs: [2, 10]\n"
            "  max_segment_bytes: 65536\n"
            "  max_total_bytes: 262144\n"
            "  latency_families: [janus_database_transaction_duration_seconds]\n"
            "slo:\n"
            "  evaluation_interval_secs: 0.25\n"
            f"  window_scale: {window_scale}\n"
        )

    result: dict = {
        "workdir": tmp,
        "schedule": "soak_full" if full else "soak_smoke",
        "engine": "postgres" if pg_url else "sqlite",
        "epochs": epochs,
        "reports_per_epoch": reports_per_epoch,
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = None
    # continuous conservation gate (ISSUE 20): the books must close at
    # EVERY epoch quiesce point — through task churn, GC really
    # deleting expired rows (expiry attribution keeps the equation
    # balanced), and continuous collection. grace 0: any residual at a
    # quiesce point is an immediate breach. The installed evaluator
    # also powers the collect loop's cross-aggregator reconciliation.
    from janus_tpu import ledger as ledger_mod

    ledger_ev = ledger_mod.install_ledger(
        leader_ds, ledger_mod.LedgerConfig(grace_s=0.0)
    )
    conservation_by_epoch: list[dict] = []

    def conservation_check() -> bool:
        doc = ledger_ev.evaluate_once()
        imb = {
            label: dict(t["imbalance"]) for label, t in doc.get("tasks", {}).items()
        }
        conservation_by_epoch.append(imb)
        return bool(imb) and all(
            v.get("ingest") == 0 and v.get("collect") == 0 for v in imb.values()
        )

    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()

        vdaf = VdafInstance.count()

        def provision_epoch_task(e: int):
            """Task churn: each epoch gets its OWN time-interval task
            with a short report_expiry_age, so by the time later epochs
            run, earlier epochs' collected rows are expired and GC has
            real rows to delete."""
            collector_kp = generate_hpke_config_and_private_key(
                config_id=100 + (e % 100)
            )
            leader_task = (
                TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
                .with_(
                    leader_aggregator_endpoint=leader_srv.url,
                    helper_aggregator_endpoint=helper_srv.url,
                    collector_hpke_config=collector_kp.config,
                    aggregator_auth_token=AuthenticationToken.random_bearer(),
                    collector_auth_token=AuthenticationToken.random_bearer(),
                    min_batch_size=1,
                    # a fine time precision keeps the report-timestamp
                    # round-down well inside the short expiry window
                    # (the default 1h precision would round every
                    # report to "already expired")
                    time_precision=Duration(5),
                    report_expiry_age=Duration(int(report_expiry_s)),
                )
                .build()
            )
            helper_task = dataclasses.replace(
                leader_task,
                role=Role.HELPER,
                hpke_keys=(generate_hpke_config_and_private_key(config_id=5),),
            )
            leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
            helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
            return leader_task, collector_kp

        # provision epoch 0 before boot so the harness can pre-warm the
        # engine programs into the shared compile cache (warm driver
        # boots; the cache covers every later epoch's identical shapes)
        epoch_tasks = [provision_epoch_task(0)]
        enable_compile_cache()
        warmup_engines(leader_ds, batch=job_size)

        flight_dirs = {
            "A": os.path.join(tmp, "flight-A"),
            "B": os.path.join(tmp, "flight-B"),
        }
        ports: dict[str, int] = {}
        for tag, failpoints in (("A", None), ("B", "flight.synthetic_leak=error:1.0")):
            port = _free_port()
            ports[tag] = port
            cfg = _driver_cfg(
                os.path.join(tmp, f"driver-{tag}.yaml"),
                leader_db,
                port,
                8,
                1.5,
                extra=soak_extra(flight_dirs[tag]),
            )
            procs.append(
                _spawn_driver(
                    cfg, key, os.path.join(tmp, f"driver-{tag}.log"), failpoints
                )
            )
        for port in ports.values():
            _wait_healthz(port)

        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=job_size
            ),
        )
        gc_leader = GarbageCollector(leader_ds, clock)
        gc_helper = GarbageCollector(helper_ds, clock)
        http = HttpClient()

        # background collection-job driver (the leader side of collect)
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.3)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()

        def aggregation_idle(deadline_s: float) -> bool:
            """Wait until no aggregation job is in a non-finished state
            (GC-deleted jobs simply vanish from the counts)."""
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                counts = leader_ds.run_tx(
                    lambda tx: tx.count_jobs_by_state(), "soak_monitor"
                )
                pending = sum(
                    n
                    for (typ, state), n in counts.items()
                    if typ == "aggregation" and state != "finished"
                )
                if pending == 0:
                    return True
                time.sleep(0.1)
            return False

        gc_deleted_total = 0
        epochs_exact = []
        epoch_details = []
        rows_by_epoch = []
        epochs_balanced: list[bool] = []
        try:
            for e in range(epochs):
                if e >= len(epoch_tasks):
                    epoch_tasks.append(provision_epoch_task(e))
                leader_task, collector_kp = epoch_tasks[e]
                params = ClientParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    helper_srv.url,
                    leader_task.time_precision,
                )
                client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
                t_epoch = clock.now()
                wave = [(i % 3 != 0) * 1 for i in range(reports_per_epoch)]
                for m in wave:
                    client.upload(m)
                creator.run_once()
                # the drivers must finish this epoch's jobs before the
                # collect — a collection issued mid-aggregation honestly
                # reports only the shares aggregated so far
                aggregation_idle(90.0)
                # collection == admitted ground truth, CONTINUOUSLY:
                # every epoch is collected exactly while churn and GC
                # keep running around it (the collect itself polls the
                # leader until the drivers finish the epoch's jobs).
                # The batch interval anchors at the epoch's UPLOAD time
                # — the fine precision means "now" at collect time can
                # be several batch units past the wave.
                tp = leader_task.time_precision
                start = t_epoch.to_batch_interval_start(tp)
                query = Query.time_interval(
                    Interval(
                        Time(start.seconds - tp.seconds), Duration(6 * tp.seconds)
                    )
                )
                collector = Collector(
                    CollectorParameters(
                        leader_task.task_id,
                        leader_srv.url,
                        leader_task.collector_auth_token,
                        collector_kp,
                    ),
                    vdaf,
                    HttpClient(),
                )
                collected = collector.collect(query, timeout_s=120.0)
                exact = (
                    collected.report_count == len(wave)
                    and collected.aggregate_result == sum(wave)
                )
                epochs_exact.append(exact)
                epoch_details.append(
                    {
                        "admitted": len(wave),
                        "sum": sum(wave),
                        "collected_count": collected.report_count,
                        "collected_sum": collected.aggregate_result,
                    }
                )
                # GC pass after every epoch: earlier epochs' rows age
                # past report_expiry_age mid-run and must REALLY vanish
                deleted = gc_leader.run_once()
                gc_helper.run_once()
                gc_deleted_total += sum(deleted.values())
                rows_by_epoch.append(
                    sum(
                        leader_ds.run_tx(
                            lambda tx: tx.count_table_rows(), "soak_monitor"
                        ).values()
                    )
                )
                # epoch quiesce point: the epoch is collected and GC
                # has run — every task's books (including earlier,
                # partially GC'd epochs) must close
                epochs_balanced.append(conservation_check())
        finally:
            stop_collect.set()
            ct.join(timeout=10)

        result["epochs_exact"] = epochs_exact
        result["epoch_details"] = epoch_details
        result["epochs_exact_ok"] = bool(epochs_exact) and all(epochs_exact)
        result["leader_rows_by_epoch"] = rows_by_epoch

        # keep GC pressure on until expiry has provably deleted rows
        # (the last epochs' reports only expire after the loop)
        gc_deadline = time.monotonic() + (60 if full else 30)
        while gc_deleted_total == 0 and time.monotonic() < gc_deadline:
            time.sleep(1.0)
            gc_deleted_total += sum(gc_leader.run_once().values())
            gc_helper.run_once()
        result["gc_deleted_rows"] = gc_deleted_total
        result["gc_deleted_ok"] = gc_deleted_total > 0

        # final quiesce: even after the late GC passes expired the last
        # epochs' rows, every epoch's books still close — expiry is an
        # ATTRIBUTED terminal, not silent row loss
        final_balanced = conservation_check()
        result["conservation_by_epoch"] = conservation_by_epoch
        result["conservation_ok"] = (
            bool(epochs_balanced) and all(epochs_balanced) and final_balanced
        )

        # --- verdict phase: the drivers idle on steady state while the
        # recorder's trailing window sheds the boot/ramp-up slope ------
        def flight_doc(tag: str, window_s: float | None = None) -> dict:
            q = f"?window_secs={window_s:g}" if window_s else ""
            return json.loads(_scrape(ports[tag], f"/debug/flight{q}"))

        judge_window_s = 6 * flight_interval_s + 2.0  # >= min_points span
        deadline = time.monotonic() + (120 if full else 45)
        fa: dict = {}
        while time.monotonic() < deadline:
            fa = flight_doc("A", judge_window_s)
            sv = fa.get("analysis", {}).get("series", {})
            # settle poll: the first trailing windows still straddle the
            # final epoch's churn; the steady-state question is whether
            # the series SETTLE to flat, not the first verdict computed
            if all(
                sv.get(n, {}).get("verdict") == "flat"
                for n in ("rss_bytes", "datastore_rows")
            ) and not fa.get("analysis", {}).get("leaking"):
                break
            time.sleep(1.0)
        series_a = fa.get("analysis", {}).get("series", {})
        result["flight_a_verdicts"] = {
            n: d.get("verdict") for n, d in series_a.items()
        }
        result["flight_a_slopes"] = {
            n: d.get("slope_per_s") for n, d in series_a.items()
        }
        # THE soak invariant: sustained load + churn + GC leaves the
        # leak-gated resource series FLAT over the trailing window
        result["zero_slope_ok"] = all(
            series_a.get(n, {}).get("verdict") == "flat"
            for n in ("rss_bytes", "datastore_rows")
        ) and not fa.get("analysis", {}).get("leaking")
        # p99 window-vs-window over the FULL recorder window (the 5s
        # judge window has too few txs per half for a stable quantile)
        latency_a = flight_doc("A").get("analysis", {}).get("latency", {})
        result["p99_verdicts"] = {f: d.get("verdict") for f, d in latency_a.items()}
        result["p99_stable_ok"] = all(
            d.get("verdict") != "degraded" for d in latency_a.values()
        )
        result["recorder_overhead_ratio"] = fa.get("overhead_ratio")
        result["overhead_ok"] = (
            fa.get("overhead_ratio") is not None and fa["overhead_ratio"] <= 0.01
        )
        ring = fa.get("ring") or {}
        result["ring"] = ring
        result["ring_budget_ok"] = (
            ring.get("segments", 0) >= 1
            and ring.get("bytes", 1 << 60) <= 262144
        )
        statusz = json.loads(_scrape(ports["A"], "/statusz"))
        fl = statusz.get("flight", {})
        age = fl.get("last_snapshot_age_s")
        result["statusz_flight_fresh_ok"] = (
            fl.get("enabled") is True
            and fl.get("running") is True
            and age is not None
            and age <= 3 * flight_interval_s + 2.0
        )
        # the gauge follows the PERIODIC analysis over the full window;
        # right after the last epoch that window can still contain the
        # fill ramp — the clean-driver claim is that it settles to zero
        no_leak = False
        settle_deadline = time.monotonic() + (60 if full else 30)
        while time.monotonic() < settle_deadline:
            leak_a = _metric_samples(
                _scrape(ports["A"], "/metrics"), "janus_flight_leak_active"
            )
            no_leak = sum(leak_a.values()) == 0.0
            if no_leak:
                break
            time.sleep(1.0)
        result["clean_driver_no_leak_ok"] = no_leak

        # --- injected-leak negative control: driver B ----------------
        leak_seen = alert_fired = False
        deadline = time.monotonic() + (120 if full else 45)
        fb: dict = {}
        while time.monotonic() < deadline:
            fb = flight_doc("B")
            leak_seen = "synthetic_leak_bytes" in (
                fb.get("analysis", {}).get("leaking") or []
            )
            if leak_seen:
                alertz = json.loads(_scrape(ports["B"], "/alertz"))
                alert_fired = any(
                    f.startswith("resource_trend/")
                    for f in alertz.get("firing", [])
                )
                if alert_fired:
                    break
            time.sleep(0.5)
        result["leak_detected_ok"] = leak_seen
        result["trend_alert_fired_ok"] = alert_fired
        leak_b = _metric_samples(
            _scrape(ports["B"], "/metrics"), "janus_flight_leak_active"
        )
        result["leak_gauge_ok"] = any(
            'series="synthetic_leak_bytes"' in k and v == 1.0
            for k, v in leak_b.items()
        )
        result["flight_b_leaking"] = fb.get("analysis", {}).get("leaking")

        # drain both drivers cleanly
        drain_ok = True
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                rc = p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = None
            drain_ok = drain_ok and rc == 0
        result["drain_ok"] = drain_ok

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        failpoints_mod = sys.modules.get("janus_tpu.failpoints")
        if failpoints_mod is not None:
            failpoints_mod.clear()
        for p in procs:
            if p.poll() is None:
                p.kill()
        if leader_srv is not None:
            leader_srv.stop()
        if helper_srv is not None:
            helper_srv.stop()
        ledger_mod.uninstall_ledger()
        leader_ds.close()
        helper_ds.close()


def run_peer_outage(
    n_reports: int = 4,
    lease_ttl_s: int = 8,
    breaker_cooldown_s: float = 1.5,
    full: bool = False,
    workdir: str | None = None,
) -> dict:
    """Peer-outage survival schedule (see module docstring): REAL
    aggregation + collection driver binaries reach the in-process
    helper only through a netsim FaultProxy; every `*_ok` key must be
    True for the run to pass."""
    import threading

    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.binary_utils import enable_compile_cache, warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.netsim import FaultProxy
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    import dataclasses

    t_run0 = time.monotonic()
    tmp = workdir or tempfile.mkdtemp(prefix="janus-peerout-")
    os.makedirs(tmp, exist_ok=True)
    key_bytes = secrets.token_bytes(16)
    key = base64.urlsafe_b64encode(key_bytes).decode().rstrip("=")
    clock = RealClock()
    leader_db = os.path.join(tmp, "leader.sqlite")
    leader_ds = Datastore(leader_db, Crypter([key_bytes]), clock)
    helper_ds = Datastore(
        os.path.join(tmp, "helper.sqlite"), Crypter([key_bytes]), clock
    )

    result: dict = {
        "workdir": tmp,
        "schedule": "peer_outage_full" if full else "peer_outage_smoke",
    }
    procs: list[subprocess.Popen] = []
    leader_srv = helper_srv = proxy = None
    try:
        helper_srv = DapServer(
            DapHttpApp(Aggregator(helper_ds, clock, Config()))
        ).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()
        # the hostile wire: driver traffic to the helper crosses this
        # proxy (the task's helper endpoint below points at it); client
        # + collector traffic goes direct so proxy stats are driver-only
        from urllib.parse import urlsplit

        helper_netloc = urlsplit(helper_srv.url).netloc
        hhost, hport = helper_netloc.split(":")
        proxy = FaultProxy(hhost, int(hport)).start()

        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=202)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=proxy.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
                # small buckets so the waves before and after the
                # blackhole land in disjoint batch intervals and the
                # two collections partition the ground truth exactly
                time_precision=Duration(2),
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=3),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        enable_compile_cache()
        warmup_engines(leader_ds)
        # warm the helper too: the drivers run with a tight per-attempt
        # timeout, so the helper must not pay a cold compile on the
        # first proxied init
        warmup_engines(helper_ds)

        # tight split so the schedule's clock stays short: 2 s attempts
        # against an 8 s lease, breaker opens after 3 failures, 1.5 s
        # cooldown, prober every 0.5 s
        extra = (
            "peer_health:\n"
            "  probe_interval_secs: 0.5\n"
            "  probe_timeout_secs: 1.0\n"
            "helper_http:\n"
            "  attempt_timeout_secs: 2.0\n"
            "  body_budget_secs: 2.0\n"
            "  max_response_mb: 8\n"
        )
        ttl = int(lease_ttl_s)
        port_a = _free_port()
        cfg_a = _driver_cfg(
            os.path.join(tmp, "agg_driver.yaml"), leader_db, port_a, ttl,
            breaker_cooldown_s, extra=extra,
        )
        drv_a = _spawn_driver(
            cfg_a, key, os.path.join(tmp, "agg_driver.log"), None
        )
        procs.append(drv_a)
        port_c = _free_port()
        cfg_c = _driver_cfg(
            os.path.join(tmp, "collect_driver.yaml"), leader_db, port_c, ttl,
            breaker_cooldown_s, extra=extra,
        )
        drv_c = _spawn_driver(
            cfg_c, key, os.path.join(tmp, "collect_driver.log"), None,
            module="janus_tpu.bin.collection_job_driver",
        )
        procs.append(drv_c)
        _wait_healthz(port_a)
        _wait_healthz(port_c)
        ports = (port_a, port_c)

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url,
            leader_task.time_precision,
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=100
            ),
        )

        acked: list[int] = []
        upload_errors: list[str] = []

        def upload_wave(measurements) -> None:
            for m in measurements:
                try:
                    client.upload(m)
                    acked.append(m)
                except Exception as e:
                    upload_errors.append(f"{type(e).__name__}: {e}")

        def agg_jobs_by_state() -> dict:
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "peerout_monitor"
            )
            return {
                s: n for (t, s), n in counts.items() if t == "aggregation"
            }

        def wait_agg_done(deadline_s: float) -> bool:
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                st = agg_jobs_by_state()
                if st and st.get("in_progress", 0) == 0:
                    return True
                time.sleep(0.1)
            return False

        def family_sum(port: int, name: str) -> float:
            return sum(
                _metric_samples(_scrape(port, "/metrics"), name).values()
            )

        def parked_value(port: int) -> float:
            samples = _metric_samples(
                _scrape(port, "/metrics"), "janus_peer_parked"
            )
            return max(samples.values()) if samples else 0.0

        def wait_parked(value: float, deadline_s: float) -> bool:
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if all(parked_value(p) == value for p in ports):
                    return True
                time.sleep(0.2)
            return False

        tp = leader_task.time_precision

        def bucket_now() -> int:
            return clock.now().to_batch_interval_start(tp).seconds

        def cross_bucket_boundary() -> int:
            """Sleep into a FRESH bucket; returns its start. Everything
            uploaded before the call stays strictly below it."""
            last = bucket_now()
            while bucket_now() <= last:
                time.sleep(0.1)
            return bucket_now()

        # --- phase 1: clean baseline through the proxy ----------------
        interval_start = bucket_now()
        upload_wave([(i % 3 != 0) * 1 for i in range(n_reports)])
        wave_a_count, wave_a_sum = len(acked), sum(acked)
        creator.run_once()
        result["baseline_agg_ok"] = wait_agg_done(120)
        result["proxy_connections_baseline"] = proxy.stats["connections_total"]
        result["proxied_baseline_ok"] = proxy.stats["connections_total"] >= 1
        boundary = cross_bucket_boundary()

        # --- phase 2: blackhole past the breaker-open threshold -------
        proxy.set_toxics("up", [{"kind": "blackhole"}])
        proxy.set_toxics("down", [{"kind": "blackhole"}])
        # uploads only touch the leader: they must keep acking 201
        upload_wave([1] * 3)
        result["uploads_during_blackhole_ok"] = not upload_errors
        creator.run_once()  # the agg driver now steps into the blackhole
        # a mid-outage collection over the BASELINE interval drives the
        # collection binary into the blackhole too (wave A is already
        # aggregated, so its step reaches the helper dial)
        collector = Collector(
            CollectorParameters(
                leader_task.task_id,
                leader_srv.url,
                leader_task.collector_auth_token,
                collector_kp,
            ),
            vdaf,
            HttpClient(),
        )
        q1 = Query.time_interval(
            Interval(Time(interval_start), Duration(boundary - interval_start))
        )
        collect1: dict = {}

        def collect1_loop():
            try:
                c = collector.collect(q1, timeout_s=240.0)
                collect1["count"] = c.report_count
                collect1["sum"] = c.aggregate_result
            except Exception as e:
                collect1["error"] = f"{type(e).__name__}: {e}"

        c1t = threading.Thread(target=collect1_loop, daemon=True)
        c1t.start()

        # both binaries must PARK: breaker opens, acquirers gate off
        result["both_parked_ok"] = wait_parked(1.0, 90)
        # while parked: claim transactions stop cold and circuit_open
        # step-backs stay bounded (no churn — that's the whole point)
        pre = {
            p: (
                family_sum(p, "janus_lease_acquire_tx_total"),
                sum(
                    v
                    for k, v in _metric_samples(
                        _scrape(p, "/metrics"), "janus_job_step_back_total"
                    ).items()
                    if "circuit_open" in k
                ),
            )
            for p in ports
        }
        time.sleep(2.0)
        frozen = True
        bounded = True
        for p in ports:
            claims_then, backs_then = pre[p]
            claims_now = family_sum(p, "janus_lease_acquire_tx_total")
            backs_now = sum(
                v
                for k, v in _metric_samples(
                    _scrape(p, "/metrics"), "janus_job_step_back_total"
                ).items()
                if "circuit_open" in k
            )
            frozen = frozen and claims_now == claims_then
            bounded = bounded and (backs_now - backs_then) <= 1
        result["claims_frozen_while_parked_ok"] = frozen
        result["step_backs_bounded_ok"] = bounded
        result["outage_seconds_counted_ok"] = all(
            family_sum(p, "janus_peer_outage_seconds_total") > 0 for p in ports
        )
        statusz = json.loads(_scrape(port_a, "/statusz"))
        ph = statusz.get("peer_health", {})
        result["statusz_peer_health_ok"] = (
            ph.get("parked") is True and bool(ph.get("peers"))
        )

        # --- phase 3: heal the wire; probes resume both drivers -------
        proxy.clear()
        result["unparked_ok"] = wait_parked(0.0, 60)
        result["recovery_agg_ok"] = wait_agg_done(120)
        c1t.join(timeout=240)
        result["collect1"] = collect1
        result["collect1_exact_ok"] = (
            collect1.get("count") == wave_a_count
            and collect1.get("sum") == wave_a_sum
        )

        if full:
            # --- latency + jitter lane --------------------------------
            lat = [{"kind": "latency", "latency_s": 0.08, "jitter_s": 0.04}]
            proxy.set_toxics("up", lat)
            proxy.set_toxics("down", lat)
            upload_wave([1] * 3)
            creator.run_once()
            result["latency_lane_ok"] = wait_agg_done(120)
            proxy.clear()
            # --- flaky mid-request resets -----------------------------
            proxy.set_toxics(
                "up", [{"kind": "reset", "after_bytes": 120, "count": 2}]
            )
            upload_wave([1] * 2)
            creator.run_once()
            result["reset_lane_ok"] = (
                wait_agg_done(120) and proxy.stats["resets"] >= 1
            )
            proxy.clear()

        # --- phase 4: slow-drip (slicer) lane -------------------------
        # one connection's responses drip in 24-byte slices, 0.7 s
        # apart: each slice resets a per-read socket timer, so only the
        # client's wall-clock body budget can end the attempt; the
        # retry rides a fresh (clean) connection
        proxy.set_toxics(
            "down",
            [{"kind": "slicer", "slice_bytes": 24, "delay_s": 0.7, "count": 1}],
        )
        upload_wave([1] * 2)
        creator.run_once()
        result["slicer_lane_ok"] = (
            wait_agg_done(150)
            and proxy.stats["toxic_fired"].get("slicer", 0) >= 1
        )
        # --- phase 5: mid-request truncation lane ---------------------
        # cut one connection's REQUEST 150 bytes in — mid-headers for
        # any HTTP request, so the fire is deterministic regardless of
        # DAP body sizes (helper responses can be under ~200 bytes
        # total, which made a response-side cut point flaky). The
        # driver sees the connection die before a response and retries
        # on a fresh (clean) wire; the helper never got a full request,
        # so no state moved. Response-side mid-body truncation (the
        # short-body-under-Content-Length detection) is pinned by
        # tests/test_netsim.py against the same proxy.
        proxy.set_toxics(
            "up", [{"kind": "truncate", "after_bytes": 150, "count": 1}]
        )
        upload_wave([1] * 2)
        creator.run_once()
        result["truncate_lane_ok"] = (
            wait_agg_done(150) and proxy.stats["truncates"] >= 1
        )
        proxy.clear()
        result["upload_errors"] = upload_errors[:5]
        result["uploads_all_acked_ok"] = not upload_errors

        # --- phase 6: collect everything after the baseline boundary --
        end = cross_bucket_boundary()
        q2 = Query.time_interval(
            Interval(Time(boundary), Duration(end - boundary))
        )
        collected = collector.collect(q2, timeout_s=240.0)
        result["collect2"] = {
            "count": collected.report_count,
            "sum": collected.aggregate_result,
        }
        # THE invariant: the two disjoint collections partition the
        # admitted ground truth exactly — through a blackhole, parking,
        # probing, slow-drip and truncation
        result["exactly_once_ok"] = (
            collect1.get("count", 0) + collected.report_count == len(acked)
            and collect1.get("sum", 0) + collected.aggregate_result == sum(acked)
        )
        result["admitted"] = len(acked)
        result["ground_truth_sum"] = sum(acked)

        # --- final gates + drain --------------------------------------
        result["lease_conflicts_ok"] = all(
            family_sum(p, "janus_lease_conflicts_total") == 0 for p in ports
        )
        result["probes_alive_ok"] = all(
            sum(
                v
                for k, v in _metric_samples(
                    _scrape(p, "/metrics"), "janus_peer_probes_total"
                ).items()
                if 'outcome="alive"' in k
            )
            >= 1
            for p in ports
        )
        result["proxy_stats"] = {
            k: v for k, v in proxy.stats.items() if k != "toxic_fired"
        } | {"toxic_fired": dict(proxy.stats["toxic_fired"])}
        drains = []
        for p, logname in ((drv_a, "agg_driver.log"), (drv_c, "collect_driver.log")):
            p.send_signal(signal.SIGTERM)
            rc = p.wait(timeout=60)
            body = open(os.path.join(tmp, logname), "rb").read()
            drains.append(rc == 0 and b"shut down" in body)
        result["drain_ok"] = all(drains)

        result["elapsed_s"] = round(time.monotonic() - t_run0, 1)
        result["ok"] = all(v for k, v in result.items() if k.endswith("_ok"))
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if proxy is not None:
            proxy.stop()
        for srv in (leader_srv, helper_srv):
            if srv is not None:
                srv.stop()
        leader_ds.close()
        helper_ds.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="fast deterministic schedule (crash + storm + collect); "
        "the default runs the full schedule incl. the post-commit crash",
    )
    ap.add_argument(
        "--scenario",
        choices=[
            "crash_storm", "db_outage", "device_hang", "pipeline", "resident",
            "cold_start", "fleet", "soak", "peer_outage",
        ],
        default="crash_storm",
        help="crash_storm = driver SIGKILL + helper storms (default); "
        "db_outage = datastore outage under upload load (journal spill, "
        "degraded serving, replay, exactly-once); device_hang = wedged "
        "device dispatch (watchdog abandon, quarantine + canary "
        "restore, host-fallback serving, exactly-once); pipeline = "
        "stage-pipelined stepper overlap proof (device lane busy while "
        "a stretched helper RTT is in flight, exactly-once); resident = "
        "device-resident accumulator flush contract (LRU eviction, "
        "quarantine sweep, SIGTERM drain each flush resident state; "
        "collections exact); cold_start = interleaved cold-cache vs "
        "warm-cache real-binary boots, restart-to-first-dispatch via "
        "/debug/boot (manifest prewarm before ready, warm < 10 s, "
        "speedup gated); fleet = N real driver replicas over one "
        "store (sharded batched claims): served-rps scaling at 1/2/4 "
        "replicas, SIGKILL + SIGTERM + restart mid-load, zero lease "
        "conflicts, exact collection; soak = endurance soak under task "
        "churn + GC deletion, judged by flight-recorder trend verdicts "
        "(zero-slope on clean driver, injected leak fires the trend "
        "alert; full run targets PostgreSQL via docker-compose.pg.yaml "
        "when JANUS_TEST_DATABASE_URL is set); peer_outage = helper "
        "behind a netsim fault proxy (blackhole past the breaker "
        "threshold parks BOTH real driver binaries, a cheap probe "
        "resumes them, slow-drip + truncation lanes recover, "
        "collections exact)",
    )
    ap.add_argument("--reports", type=int, default=0, help="0 = schedule default")
    ap.add_argument("--json", action="store_true", help="print the result record as JSON")
    ap.add_argument("--workdir", default=None, help="keep artifacts here (default: temp dir)")
    args = ap.parse_args(argv)

    if args.scenario == "db_outage":
        result = run_db_outage(
            n_warm=args.reports or (4 if args.smoke else 10),
            outage_hold_s=1.5 if args.smoke else 5.0,
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "device_hang":
        result = run_device_hang(
            n_reports=args.reports or (5 if args.smoke else 12),
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "pipeline":
        result = run_pipeline(
            n_reports=args.reports or (24 if args.smoke else 60),
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "resident":
        result = run_resident(
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "cold_start":
        result = run_cold_start(
            pairs=1 if args.smoke else 2,
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "fleet":
        result = run_fleet(
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "soak":
        result = run_soak(
            epochs=4 if args.smoke else 12,
            reports_per_epoch=args.reports or (8 if args.smoke else 24),
            report_expiry_s=30.0 if args.smoke else 120.0,
            full=not args.smoke,
            workdir=args.workdir,
        )
    elif args.scenario == "peer_outage":
        result = run_peer_outage(
            n_reports=args.reports or (4 if args.smoke else 8),
            full=not args.smoke,
            workdir=args.workdir,
        )
    else:
        n = args.reports or (5 if args.smoke else 12)
        result = run_chaos(
            n_reports=n,
            full=not args.smoke,
            workdir=args.workdir,
        )
    if args.json:
        print(json.dumps(result))
    else:
        print(json.dumps(result, indent=2))
    if not result.get("ok"):
        failed = [k for k, v in result.items() if k.endswith("_ok") and not v]
        print(f"CHAOS FAILED: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
