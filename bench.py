"""Benchmark: batched two-party Prio3 prepare+accumulate throughput.

Measures the north-star metric of BASELINE.md: report-shares/sec/chip
for the full two-party prepare + accumulate step (leader init + helper
init + combine/decide + masked aggregate — everything the reference
does per report in aggregation_job_driver.rs:329-402,530-726 and
aggregator.rs:1775-1826), on whatever accelerator JAX exposes.

CPU baseline: the host oracle (janus_tpu.vdaf.reference) timed on a few
reports and extrapolated. The reference's own prio-rs CPU path cannot
run in this image (no Rust toolchain); the host oracle stands in as
the measured-CPU column of BASELINE.md. vs_baseline is
device_throughput / host_throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def run_served(inst, n_reports: int, job_size: int, progress) -> dict:
    """End-to-end served throughput: reports through the real helper +
    leader HTTP handlers (HPKE opens, wire decode, SQLite writes, the
    device engine) on an in-process loopback pair.

    Measures what the device-step bench deliberately excludes — the
    serving shell around the engine (VERDICT Weak #4; the reference's
    hot path aggregator.rs:1561-1890 includes all of it).
    """
    import time as _time

    import dataclasses as _dc

    import numpy as np

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.testing import make_wire_reports, random_measurements

    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    # supervise the serving store like the real binaries do, so the
    # record's datastore_up/janus_datastore_up series carry the real
    # outage-survival signal (unsupervised, the gauge would read a
    # misleading default 0)
    leader_eph.datastore.start_supervision(probe_interval_s=2.0)
    leader_agg = Aggregator(leader_eph.datastore, clock, Config())
    helper_agg = Aggregator(helper_eph.datastore, clock, Config())
    leader_srv = DapServer(DapHttpApp(leader_agg)).start()
    helper_srv = DapServer(DapHttpApp(helper_agg)).start()
    # the SLO engine runs through the served phase like in the real
    # binaries (default definitions, fast cadence so the windows hold
    # real samples by scrape time) — the record's alertz_ok and the
    # exemplar round-trip come from the live /alertz + OpenMetrics
    # scrape at the end
    from janus_tpu import slo as _slo

    _slo.install_slo_engine(_slo.SloEngineConfig(evaluation_interval_s=0.5))
    # the continuous profiler runs through the served phase like in the
    # real binaries (janus_main installs it by default): the record's
    # profiler rider reads the per-role shares and the device cost
    # ledger's µs/report attribution at the end
    from janus_tpu import profiler as _prof

    # 97 Hz (vs the production 19): the served aggregate phase is a
    # fraction of a second on CPU, and the rider's device-lane self
    # share needs real samples inside it; still well under the 2%
    # overhead budget (the rider records the measured ratio)
    _prof.install_profiler(_prof.ProfilerConfig(hz=97.0, window_secs=15.0))
    try:
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), inst, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = _dc.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_eph.datastore.run_tx(lambda tx: tx.put_task(leader_task))
        helper_eph.datastore.run_tx(lambda tx: tx.put_task(helper_task))

        # warm both aggregators' engines before timing (production boots
        # with warmup_engines_at_boot; first-compile must not pollute
        # the steady-state serving numbers)
        from janus_tpu.binary_utils import warmup_engines

        # warm every batch bucket the run will actually use: full jobs
        # of job_size and the remainder job (bucketed separately)
        warm_sizes = {min(job_size, n_reports)}
        if n_reports % job_size:
            warm_sizes.add(n_reports % job_size)
        t0 = _time.time()
        for ws in sorted(warm_sizes):
            warmup_engines(leader_eph.datastore, batch=ws)
            warmup_engines(helper_eph.datastore, batch=ws)
        warmup_s = _time.time() - t0
        progress["t"] = time.monotonic()

        rng = np.random.default_rng(0x5E12)
        meas = random_measurements(inst, n_reports, rng)
        t0 = _time.time()
        when = clock.now().to_batch_interval_start(leader_task.time_precision)
        reports = make_wire_reports(
            inst,
            meas,
            leader_task.task_id,
            leader_task.hpke_keys[0].config,
            helper_task.hpke_keys[0].config,
            when,
            seed=2,
        )
        stage_s = _time.time() - t0
        progress["t"] = time.monotonic()

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        # concurrent upload clients: the write batcher amortizes the
        # datastore tx across in-flight uploads (reference
        # ReportWriteBatcher semantics) — a serial client only measures
        # the flush delay, not throughput
        from concurrent.futures import ThreadPoolExecutor

        def _upload(r):
            for attempt in (0, 1):
                try:
                    status, body = http.put(
                        params.upload_uri(),
                        r.to_bytes(),
                        {"Content-Type": "application/dap-report"},
                    )
                except (ConnectionError, OSError):
                    if attempt:
                        raise
                    continue
                if status == 201:
                    return
                if attempt and status in (400, 409) and (
                    b"reportRejected" in body or b"replay" in body
                ):
                    # the first PUT landed but its 201 was lost on the
                    # wire; the server's duplicate-report answer on the
                    # retry is success, not a bench failure
                    return
                break
            raise AssertionError(f"upload failed: {status} {body!r}")

        # ingest phase (docs/INGEST.md): serial baseline first — one
        # report in flight, so the decrypt pool cannot overlap work —
        # then the 16-way burst the staged pipeline was built for; the
        # ratio is the pipelining win on this host. Shed accounting
        # rides along (0 unless admission buckets are configured).
        from janus_tpu import metrics as _metrics

        shed0 = _metrics.upload_shed_counter.total()
        n_serial = max(2, min(32, n_reports // 4))
        t0 = _time.time()
        for r in reports[:n_serial]:
            _upload(r)
        serial_s = _time.time() - t0
        serial_rps = n_serial / serial_s if serial_s > 0 else float("inf")
        progress["t"] = time.monotonic()
        t0 = _time.time()
        with ThreadPoolExecutor(max_workers=16) as pool:
            list(pool.map(_upload, reports[n_serial:]))
        upload_s = _time.time() - t0
        ingest_rps = (n_reports - n_serial) / upload_s if upload_s > 0 else float("inf")
        shed_total = _metrics.upload_shed_counter.total() - shed0
        progress["t"] = time.monotonic()

        # server-side ingest capacity, isolated from the loopback
        # client's own Python cost (which shares the GIL with the
        # server above): the OLD upload architecture — one thread, one
        # transaction per report — vs the staged pipeline fed directly,
        # on fresh stores so every commit is a real insert
        from janus_tpu.aggregator.core import TaskAggregator
        from janus_tpu.aggregator.report_writer import ReportWriteBatcher
        from janus_tpu.ingest import IngestPipeline

        sample = reports[: min(96, n_reports)]
        eph_a = EphemeralDatastore(clock=clock)
        eph_b = EphemeralDatastore(clock=clock)
        try:
            eph_a.datastore.run_tx(lambda tx: tx.put_task(leader_task))
            eph_b.datastore.run_tx(lambda tx: tx.put_task(leader_task))
            ta = TaskAggregator(leader_task, Config())
            t0 = _time.time()
            for r in sample:
                ta.handle_upload(eph_a.datastore, clock, r, None)
            serial_path_s = _time.time() - t0
            progress["t"] = time.monotonic()
            writer = ReportWriteBatcher(eph_b.datastore, 100, 0)
            pipe = IngestPipeline(writer, queue_depth=len(sample))
            try:
                t0 = _time.time()
                tickets = [pipe.submit(ta, clock, r.to_bytes()) for r in sample]
                assert all(t.result(timeout_s=60) for t in tickets)
                pipeline_s = _time.time() - t0
            finally:
                pipe.close()
                writer.close()
        finally:
            eph_a.cleanup()
            eph_b.cleanup()
        progress["t"] = time.monotonic()

        creator = AggregationJobCreator(
            leader_eph.datastore,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=job_size
            ),
        )
        # resident accumulators on (ISSUE 12): the masked accumulate
        # merges into device-resident per-bucket buffers (no per-job
        # share fetch); the drain flush below writes them out before
        # collection — the production resident-mode shape
        from janus_tpu.aggregator.aggregation_job_driver import (
            AggregationJobDriverConfig,
            ResidentConfig,
        )

        driver = AggregationJobDriver(
            leader_eph.datastore,
            http,
            AggregationJobDriverConfig(
                resident=ResidentConfig(enabled=True, flush_interval_s=3600.0)
            ),
        )
        # the production stepper: the stage pipeline (ISSUE 9) — job
        # B's read+staging and HTTP legs overlap job A's device phases
        # behind the serialized device lane (double-buffered staging on
        # by default: job k+1's H2D overlaps job k's dispatch)
        from janus_tpu.aggregator.step_pipeline import StepPipeline, StepPipelineConfig

        pipeline = StepPipeline(driver, StepPipelineConfig())
        jd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=4),
            driver.acquirer(),
            driver.stepper,
            pipeline=pipeline,
        )
        hd_h2d0 = _m.engine_hd_bytes_total.get(direction="h2d")
        hd_d2h0 = _m.engine_hd_bytes_total.get(direction="d2h")
        prestage0 = {
            o: _m.engine_prestage_total.get(outcome=o) for o in ("hit", "fallback")
        }
        t0 = _time.time()
        creator.run_once()
        while jd.run_once():
            progress["t"] = time.monotonic()
        resident_flushed = driver.flush_resident_state(reason="drain")
        aggregate_s = _time.time() - t0
        resident_rider = {
            "enabled": True,
            "flushed_buffers": resident_flushed,
            "hd_bytes_h2d": _m.engine_hd_bytes_total.get(direction="h2d") - hd_h2d0,
            "hd_bytes_d2h": _m.engine_hd_bytes_total.get(direction="d2h") - hd_d2h0,
            "prestage_hits": _m.engine_prestage_total.get(outcome="hit")
            - prestage0["hit"],
            "prestage_fallbacks": _m.engine_prestage_total.get(outcome="fallback")
            - prestage0["fallback"],
        }
        resident_rider["hd_bytes_per_report"] = round(
            (resident_rider["hd_bytes_h2d"] + resident_rider["hd_bytes_d2h"])
            / max(1, n_reports),
            1,
        )
        progress["t"] = time.monotonic()
        # p50/p95 aggregation-job step latency from the flight-recorder
        # digest (PR 5) — BASELINE's second metric, read BEFORE the
        # collection driver adds its own job.step observations
        from janus_tpu import trace as _tr

        _step_digest = (
            _tr.flight_recorder().snapshot(recent_limit=0)["digests"].get("job.step")
        )
        step_pipeline_status = pipeline.status()

        collector = Collector(
            CollectorParameters(
                leader_task.task_id,
                leader_srv.url,
                leader_task.collector_auth_token,
                collector_kp,
            ),
            inst,
            http,
        )
        query = Query.time_interval(
            Interval(Time(when.seconds - 3600), Duration(3600 * 4))
        )
        t0 = _time.time()
        job_id = collector.start_collection(query)
        cdriver = CollectionJobDriver(leader_eph.datastore, http)
        cjd = JobDriver(
            JobDriverConfig(max_concurrent_job_workers=1),
            cdriver.acquirer(),
            cdriver.stepper,
        )
        cjd.run_once()
        result = collector.poll_once(job_id, query)
        collect_s = _time.time() - t0
        assert result.report_count == n_reports, result.report_count

        # scrape the real health listener after the serving run: one
        # sampling pass against the leader store, then /metrics +
        # /statusz over HTTP, validated with the shared exposition
        # parser — so every BENCH json carries the engine/job metric
        # snapshot even when the accelerator phases stall
        scrape_ok = False
        scrape_errors: list = []
        alertz_ok = False
        alertz_firing: list = []
        exemplar_roundtrip: dict = {}
        try:
            scrape = _scrape_health_listener(ds=leader_eph.datastore)
            scrape["server"].stop()
            scrape_ok = not scrape["errors"]
            scrape_errors = scrape["errors"][:5]
            alertz = scrape["alertz"]
            alertz_ok = (
                alertz.get("enabled") is True
                and {"firing", "alerts", "slos"} <= set(alertz)
                and len(alertz["slos"]) >= 5
                and all("burn_rates" in s for s in alertz["slos"])
                and not scrape["openmetrics_errors"]
            )
            alertz_firing = alertz.get("firing", [])
            # exemplar resolution over live HTTP: a latency exemplar in
            # the OpenMetrics scrape links to a /debug/traces capture
            exemplar_roundtrip = _exemplar_roundtrip(scrape)
        except Exception as e:  # the bench record must survive
            scrape_errors = [f"scrape failed: {e}"]
        # profiler rider (ISSUE 13): top roles by wall-clock share over
        # the served run, the cost ledger's live µs/report table (the
        # accumulate row is the acceptance cross-check against the
        # served device time) and the boot timeline (None in-process —
        # janus_main owns the boot record in the real binaries)
        prof_doc = _prof.PROFILER.profile_json()
        profiler_rider = {
            "enabled": prof_doc["enabled"],
            "samples": prof_doc["samples"],
            "overhead_ratio": prof_doc["overhead_ratio"],
            "top_roles": [
                {"role": r, "total_pct": v["total_pct"], "self_pct": v["self_pct"]}
                for r, v in sorted(
                    prof_doc["roles"].items(), key=lambda kv: -kv[1]["total_pct"]
                )[:3]
            ],
            "device_lane_self_pct": prof_doc["roles"]
            .get("device_lane", {})
            .get("self_pct", 0.0),
            "us_per_report": _prof.DEVICE_COST.us_per_report(),
            "boot_total_s": _prof.BOOT.snapshot().get("total_s"),
        }
        return {
            "n_reports": n_reports,
            "warmup_s": round(warmup_s, 2),
            "stage_s": round(stage_s, 2),
            "upload_serial_rps": round(serial_rps, 2),
            "ingest_rps": round(ingest_rps, 2),
            "upload_rps": round(ingest_rps, 2),  # legacy name
            "ingest_vs_serial": round(ingest_rps / serial_rps, 2),
            "upload_shed_total": shed_total,
            # old architecture (one thread, one tx per report) vs the
            # staged pipeline, pure server-side
            "single_thread_upload_rps": round(len(sample) / serial_path_s, 2),
            "ingest_pipeline_rps": round(len(sample) / pipeline_s, 2),
            "ingest_pipeline_speedup": round(serial_path_s / pipeline_s, 2),
            "served_aggregate_rps": round(n_reports / aggregate_s, 2),
            # BASELINE's second metric: aggregation-job step latency
            # quantiles, sourced from the flight-recorder digests
            "agg_job_step_latency": (
                {
                    "p50_s": _step_digest["p50_s"],
                    "p95_s": _step_digest["p95_s"],
                    "mean_s": _step_digest["mean_s"],
                    "count": _step_digest["count"],
                }
                if _step_digest
                else None
            ),
            # stage-pipeline overlap proof for the measured form of the
            # step_pipeline record (the dry-run form rides pipeline_smoke)
            "step_pipeline": {
                "overlap_ratio": step_pipeline_status["overlap_ratio"],
                "overlapped_dispatches": step_pipeline_status["overlapped_dispatches"],
                "device_lane_busy_ratio": step_pipeline_status["device_lane"]["busy_ratio"],
                "device_lane_dispatches": step_pipeline_status["device_lane"]["dispatches"],
            },
            # resident accumulators + double-buffered staging over the
            # served run (ISSUE 12): drain-flushed buffer count, the
            # engine layer's host<->device bytes/report, and the
            # prestage hit/fallback split
            "resident": resident_rider,
            "collect_s": round(collect_s, 2),
            "metrics_scrape_valid": scrape_ok,
            # SLO engine + exemplar surface over the served run (ISSUE
            # 10): /alertz well-formed with burn rates for every
            # default SLO, and an OpenMetrics exemplar resolving to a
            # live /debug/traces span
            "alertz_ok": alertz_ok,
            "alertz_firing": alertz_firing,
            "exemplar_roundtrip": exemplar_roundtrip,
            **({"metrics_scrape_errors": scrape_errors} if scrape_errors else {}),
            # datastore/journal state at the end of the served run (the
            # outage-survival dashboard series; full samples ride the
            # snapshot below via the janus_datastore_/janus_upload_
            # journal_ prefixes)
            "datastore_up": _m.datastore_up.get(),
            "upload_journal_depth": _m.upload_journal_depth.get(),
            # continuous profiler over the served run (ISSUE 13)
            "profiler": profiler_rider,
            "metrics_snapshot": _metrics_snapshot_rider(),
        }
    finally:
        _prof.uninstall_profiler()
        _slo.uninstall_slo_engine()
        try:
            pipeline.close()
        except NameError:
            pass  # failed before the aggregate phase built it
        leader_srv.stop()
        helper_srv.stop()
        leader_eph.cleanup()
        helper_eph.cleanup()


def run_poplar1(args, backend, progress, watchdog) -> None:
    """Poplar1 two-party prepare throughput: batched device IDPF eval +
    quadratic sketch (vdaf.poplar1_jax) at the declared parity config
    (Poplar1<XofShake128,16>, reference aggregator.rs:1096), leaf level,
    256 queried prefixes. Host baseline: the per-report host walk
    (vdaf.poplar1.Poplar1.prepare_init), extrapolated."""
    import secrets
    import time as _time

    import numpy as np

    from janus_tpu.vdaf.poplar1 import Poplar1, Poplar1AggParam
    from janus_tpu.vdaf.poplar1_jax import prepare_init_batched

    bits = 16
    level = bits - 1
    n_prefixes = 256
    batch = args.batch or (512 if backend != "cpu" else 32)
    verify_key = bytes(range(16))
    poplar = Poplar1(bits)
    rng = np.random.default_rng(0xB0B)

    t0 = _time.time()
    alphas = [int(rng.integers(0, 1 << bits)) for _ in range(batch)]
    keys0, keys1 = [], []
    for a in alphas:
        _, (k0, k1) = poplar.shard(a)
        keys0.append(k0)
        keys1.append(k1)
    prefixes = tuple(sorted(rng.choice(1 << bits, size=n_prefixes, replace=False).tolist()))
    param = Poplar1AggParam(level, prefixes)
    nonces = [secrets.token_bytes(16) for _ in alphas]
    print(f"[bench] poplar1 shard(batch={batch}): {_time.time()-t0:.1f}s", file=sys.stderr, flush=True)
    progress["t"] = time.monotonic()

    def both_parties():
        # two-party prepare: both aggregators' round-1 (device), sketch
        # combine on host ints (tiny). Return value forces the fetch.
        y0, A0, B0, a0, c0 = prepare_init_batched(bits, 0, keys0, param, verify_key, nonces)
        y1, A1, B1, a1, c1 = prepare_init_batched(bits, 1, keys1, param, verify_key, nonces)
        F = poplar.idpf.field_at(level)
        ok = 0
        for i in range(batch):
            A = F.add(A0[i], A1[i])
            B = F.add(B0[i], B1[i])
            s0 = F.neg(F.sub(F.mul(2 % F.MODULUS, F.mul(A, a0[i])), c0[i]))
            s0 = F.add(s0, F.sub(F.mul(A, A), B))
            s1 = F.neg(F.sub(F.mul(2 % F.MODULUS, F.mul(A, a1[i])), c1[i]))
            ok += int(F.add(s0, s1) == 0)
        assert ok == batch, f"sketch failed: {ok}/{batch}"
        return ok

    t0 = _time.time()
    both_parties()
    compile_s = _time.time() - t0
    progress["t"] = time.monotonic()
    t0 = _time.time()
    iters = max(2, args.iters)
    for _ in range(iters):
        both_parties()
        progress["t"] = time.monotonic()
    device_rps = batch * iters / (_time.time() - t0)

    # host baseline: the scalar walk on a few reports
    hr = min(args.host_reports, batch)
    t0 = _time.time()
    for i in range(hr):
        poplar.prepare_init(0, keys0[i], param, verify_key, nonces[i])
        poplar.prepare_init(1, keys1[i], param, verify_key, nonces[i])
        progress["t"] = time.monotonic()
    host_rps = hr / (_time.time() - t0)

    progress["done"] = True
    if watchdog is not None:
        watchdog.cancel()
    print(
        json.dumps(
            {
                "metric": "poplar1_two_party_prepare",
                "value": round(device_rps, 2),
                "unit": "reports_per_sec_per_chip",
                "vs_baseline": round(device_rps / host_rps, 2),
                "backend": backend,
                "batch": batch,
                "bits": bits,
                "level": level,
                "prefixes": n_prefixes,
                "iters": iters,
                "compile_s": round(compile_s, 1),
                "host_walk_rps": round(host_rps, 3),
            }
        )
    )


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (shared helper in binary_utils):
    re-runs of the same config skip the multi-minute compile."""
    from janus_tpu.binary_utils import enable_compile_cache

    enable_compile_cache()


def _make_inst(args, ap):
    """The BASELINE.md measurement config for the parsed args (shared
    by the measured run and --dry-run)."""
    import dataclasses

    from janus_tpu.vdaf.registry import VdafInstance

    if args.length and args.config in ("count", "sum"):
        ap.error(f"--length has no meaning for --config {args.config}")
    L = args.length
    inst = {
        "count": VdafInstance.count(),
        "sum": VdafInstance.sum(bits=32),
        "sumvec": VdafInstance.sum_vec(length=L or 1000, bits=16),
        "histogram": VdafInstance.histogram(length=L or 10000),
        "fixedpoint": VdafInstance.fixed_point_vec(length=L or 1000, bits=16),
        # block-sparse north star (ISSUE 17): logical len-1M accumulator,
        # each report carries <= 16 live blocks of 64 — device work rides
        # the COMPACT encoding (1024 lanes), the scatter-merge owns the
        # logical length
        "sparse": VdafInstance.sparse_sumvec(
            bits=16, length=L or 1_000_000, block_size=64, max_blocks=16
        ),
    }[args.config]
    if args.xof_mode != "fast":
        inst = dataclasses.replace(inst, xof_mode=args.xof_mode)
    return inst


def _oom_fallback_smoke() -> dict:
    """Exercise the EngineCache OOM machinery on a toy circuit with an
    injected RESOURCE_EXHAUSTED: one flaky round must survive via the
    halved-bucket retry, a persistently failing device must end in the
    HostEngineCache fallback — with correct results both times and no
    exception escaping. Runs anywhere (CPU backend); CI's --dry-run
    smoke covers the serving path's new failure handling."""
    import numpy as np

    from janus_tpu.aggregator import engine_cache as ec
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = VdafInstance.sum_vec(length=4, bits=2)
    vk = bytes(range(16))
    rng = np.random.default_rng(5)
    meas = random_measurements(inst, 4, rng)
    (nonce, public, meas_v, proof, blind0, seeds, blind1), _ = make_report_batch(
        inst, meas, seed=1
    )
    ok = np.ones(4, dtype=bool)

    # one injected OOM -> halved-bucket retry succeeds (observed bucket
    # MIN_BUCKET=32 stays above the floor even on an 8-device mesh)
    eng = ec.EngineCache(inst, vk)
    eng.bucket_cap = 32
    inner = eng._helper_init_inner
    fails = {"n": 0}

    def flaky(*a, **k):
        if fails["n"] < 1:
            fails["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED: injected (dry-run smoke)")
        return inner(*a, **k)

    eng._helper_init_inner = flaky
    _, seed0, ver0, part0 = eng.leader_init(nonce, public, meas_v, proof, blind0)
    _, mask, _ = eng.helper_init(nonce, public, seeds, blind1, ver0, part0, ok)
    retry_ok = bool(mask.all()) and fails["n"] == 1 and eng._host_fallback is None

    # persistent OOM -> bucket floor -> host fallback, still correct
    eng2 = ec.EngineCache(inst, vk)

    def always_oom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: injected (dry-run smoke)")

    eng2._helper_init_inner = always_oom
    out1, mask2, _ = eng2.helper_init(nonce, public, seeds, blind1, ver0, part0, ok)
    fallback_ok = bool(mask2.all()) and eng2._host_fallback is not None
    return {
        "halved_retry_ok": retry_ok,
        "bucket_cap_after_retry": eng.bucket_cap,
        "host_fallback_ok": fallback_ok,
    }


def _sparse_scatter_smoke() -> dict:
    """Block-sparse scatter-merge end to end on a toy geometry (CPU
    backend): two-party prepare over sparse reports, then scatter-add of
    each verified report's blocks into the dense logical accumulator via
    BOTH device paths — the classic per-bucket aggregate_sparse reduce
    and the pending-delta resident_merge — asserting the released
    aggregate is bit-identical to the dense oracle computed by expanding
    the plaintext measurements on host. Also proves the scatter path
    actually ran (engine scatter counters + a scatter_merge cost-ledger
    op with nonzero rows)."""
    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.messages import Duration, Interval, Time
    from janus_tpu.profiler import DEVICE_COST
    from janus_tpu.vdaf.registry import VdafInstance, circuit_for
    from janus_tpu.vdaf.testing import (
        make_report_batch,
        random_measurements,
        sparse_compact_batch,
    )
    from janus_tpu.vdaf.wire import flat_scatter_indices

    inst = VdafInstance.sparse_sumvec(bits=3, length=48, block_size=4, max_blocks=3)
    circ = circuit_for(inst)
    rng = np.random.default_rng(11)
    n = 8
    meas = random_measurements(inst, n, rng)
    (nonce, public, mv, proof, blind0, seeds, blind1), _ = make_report_batch(
        inst, meas, seed=3
    )
    _, block_idx = sparse_compact_batch(inst, meas)
    flat_idx = flat_scatter_indices(block_idx, circ)
    ok = np.ones(n, dtype=bool)

    eng = EngineCache(inst, bytes(range(16)))
    out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
    out1, accept, _ = eng.helper_init(nonce, public, seeds, blind1, ver0, part0, ok)
    share0 = eng.aggregate_sparse(out0, accept, flat_idx)
    share1 = eng.aggregate_sparse(out1, accept, flat_idx)
    p = circ.FIELD.MODULUS
    got = [(int(x) + int(y)) % p for x, y in zip(share0, share1)]
    # dense oracle: expand each plaintext pair-measurement and sum mod p
    want = [0] * circ.logical_length
    for m in meas:
        for bi, block in m:
            for off, v in enumerate(block):
                k = bi * circ.block_size + off
                want[k] = (want[k] + v) % p
    classic_identical = got == want and bool(accept.all())

    # resident path: the deltas defer the scatter to merge time, then a
    # take releases the logical-length share
    deltas = eng.aggregate_pending(out0, np.zeros(n, dtype=np.int32), 1, flat_idx=flat_idx)
    iv = Interval(Time(0), Duration(3600))
    eng.resident_merge([((b"task", b"", b"bid"), 0, n, iv)], deltas)
    recs = eng.resident_take()
    deltas1 = eng.aggregate_pending(out1, np.zeros(n, dtype=np.int32), 1, flat_idx=flat_idx)
    recs1 = eng.fetch_delta_records([((b"task", b"", b"bid"), 0, n, iv)], deltas1)
    resident = [
        (int(x) + int(y)) % p
        for x, y in zip(recs[0]["share"], recs1[0]["share"])
    ]
    resident_identical = resident == want
    ledger = DEVICE_COST.status()["entries"]
    scatter_rows = sum(
        e["rows"] for e in ledger if e["op"] == "scatter_merge" and e["vdaf"] == inst.kind
    )
    return {
        "classic_identical": classic_identical,
        "resident_identical": resident_identical,
        "scatter_path_observed": eng._scatter_rows > 0 and scatter_rows > 0,
        "scatter_rows": eng._scatter_rows,
        "block_occupancy": eng._sparse_last_occupancy,
        "mesh_fallback_reason": eng.mesh_fallback_reason,
    }


def _ingest_shed_smoke() -> dict:
    """Drive a burst of real uploads through the admission-controlled
    ingest pipeline over loopback HTTP with a deliberately tiny token
    bucket: the first `burst` uploads must commit (exactly once), the
    rest must shed `429 + Retry-After`, and `janus_upload_shed_total`
    must account for every rejection. CPU-only, no accelerator — CI's
    --dry-run smoke covers the serving shed path on every test run."""
    from janus_tpu import metrics as _m
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    # burst of 3 then a ~glacial refill: uploads 4..8 shed deterministically
    cfg = Config(
        upload_bucket_rate=0.001,
        upload_bucket_burst=3,
        ingest_decrypt_workers=2,
        ingest_queue_depth=8,
    )
    agg = Aggregator(eph.datastore, clock, cfg)
    srv = DapServer(DapHttpApp(agg), max_handler_threads=4).start()
    try:
        vdaf = VdafInstance.count()
        leader_kp = generate_hpke_config_and_private_key(config_id=0)
        helper_kp = generate_hpke_config_and_private_key(config_id=1)
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=srv.url,
                helper_aggregator_endpoint=srv.url,
                hpke_keys=(leader_kp,),
                min_batch_size=1,
            )
            .build()
        )
        eph.datastore.run_tx(lambda tx: tx.put_task(task))
        params = ClientParameters(task.task_id, srv.url, srv.url, task.time_precision)
        client = Client(params, vdaf, leader_kp.config, helper_kp.config, clock=clock)
        http = HttpClient()
        shed0 = _m.upload_shed_counter.total()
        results = []
        for _ in range(8):
            report = client.prepare_report(1)
            status, _body = http.put(
                params.upload_uri(),
                report.to_bytes(),
                {"Content-Type": "application/dap-report"},
            )
            retry_after = next(
                (
                    v
                    for k, v in http.last_response_headers.items()
                    if k.lower() == "retry-after"
                ),
                None,
            )
            results.append((status, retry_after))
        accepted = sum(1 for s, _ in results if s == 201)
        shed = [r for r in results if r[0] == 429]
        stored, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        return {
            "accepted": accepted,
            "shed": len(shed),
            "shed_counter_delta": _m.upload_shed_counter.total() - shed0,
            "retry_after_present": bool(shed)
            and all(ra is not None and float(ra) >= 1 for _, ra in shed),
            "stored_reports": int(stored),
            "committed_exactly_once": int(stored) == accepted,
        }
    finally:
        srv.stop()
        eph.cleanup()


def _tracing_overhead(iters: int = 1000) -> dict:
    """Measure the span() hot path instead of assuming it: a synthetic
    per-report workload wrapped in the engine's span shape (one outer +
    three phase spans, the same names the span->metric bridge observes)
    timed with tracing disabled, with the Chrome-trace writer, and with
    the OTLP exporter recording spans (export posts go to an
    unroutable endpoint and fail in the background thread — the hot
    path cost is record_span, not the network). Also reports the bare
    cost of one span() enter/exit per mode."""
    import tempfile
    import time as _time

    import numpy as np

    from janus_tpu import trace as trace_mod
    from janus_tpu.trace import span

    a = np.random.default_rng(7).random((64, 64))
    b = a.T.copy()

    def workload_plain():
        a @ b
        a @ b
        a @ b

    def workload_traced():
        with span("bench.prepare", vdaf="bench", batch=64):
            with span("bench.prepare.put", vdaf="bench"):
                a @ b
            with span("bench.prepare.dispatch", vdaf="bench"):
                a @ b
            with span("bench.prepare.fetch", vdaf="bench"):
                a @ b

    def measure(fn=None) -> tuple[float, float]:
        """(workload iters/s, bare span cost ns)."""
        fn = fn or workload_traced
        t0 = _time.perf_counter()
        for _ in range(iters):
            fn()
        rps = iters / (_time.perf_counter() - t0)
        n_bare = 10_000
        t0 = _time.perf_counter()
        for _ in range(n_bare):
            with span("bench.overhead.noop"):
                pass
        span_ns = (_time.perf_counter() - t0) / n_bare * 1e9
        return rps, span_ns

    # save/restore the process-global exporters so the phase cannot
    # leak a writer into the rest of the run
    saved_writer = trace_mod._chrome_writer
    saved_otlp = trace_mod._otlp_exporter
    saved_recorder = trace_mod._flight_recorder
    tmp = tempfile.mkdtemp(prefix="janus-bench-trace-")

    class _NullRecorder:  # flight-recorder-off baseline (it is
        def record(self, *a, **k):  # always armed in production)
            pass

    try:
        trace_mod._chrome_writer = None
        trace_mod._otlp_exporter = None
        # warm numpy/BLAS and the span machinery before ANY measurement:
        # on a loaded 2-core host, thread-pool spin-up landing inside
        # the first timed mode skews the ratios
        for _ in range(200):
            workload_plain()
            workload_traced()
        # no-span baseline: disabled_vs_baseline isolates the cost of
        # the span machinery itself (contextvar + PRNG + the
        # span->metric bridge lookup + the always-armed flight
        # recorder) with no exporter configured
        baseline_rps, _ = measure(workload_plain)
        # recorder-off vs recorder-armed: the marginal cost of the
        # always-on flight recorder itself (ISSUE 6 "near-free" claim)
        trace_mod._flight_recorder = _NullRecorder()
        recorder_off_rps, recorder_off_ns = measure()
        trace_mod._flight_recorder = saved_recorder
        disabled_rps, disabled_ns = measure()

        trace_mod.install_chrome_trace(os.path.join(tmp, "overhead.json"))
        chrome_rps, chrome_ns = measure()
        trace_mod._chrome_writer.close()
        trace_mod._chrome_writer = None

        # long flush interval: no mid-measurement flush; shutdown's
        # final flush fails fast (connection refused on loopback)
        exporter = trace_mod.OtlpExporter(
            "http://127.0.0.1:9", flush_interval_s=3600.0
        )
        trace_mod._otlp_exporter = exporter
        otlp_rps, otlp_ns = measure()
        trace_mod._otlp_exporter = None
        exporter.shutdown()
    finally:
        trace_mod._chrome_writer = saved_writer
        trace_mod._otlp_exporter = saved_otlp
        trace_mod._flight_recorder = saved_recorder
    return {
        "iters": iters,
        "spans_per_iter": 4,
        "baseline_rps": round(baseline_rps, 1),
        "disabled_vs_baseline": round(disabled_rps / baseline_rps, 3),
        "disabled_rps": round(disabled_rps, 1),
        "recorder_off_rps": round(recorder_off_rps, 1),
        "chrome_rps": round(chrome_rps, 1),
        "otlp_rps": round(otlp_rps, 1),
        "chrome_vs_disabled": round(chrome_rps / disabled_rps, 3),
        "otlp_vs_disabled": round(otlp_rps / disabled_rps, 3),
        "span_ns_recorder_off": round(recorder_off_ns),
        "span_ns_disabled": round(disabled_ns),
        "span_ns_chrome": round(chrome_ns),
        "span_ns_otlp": round(otlp_ns),
    }


# /metrics families the BENCH json rider carries (the full snapshot
# would bloat the record; these are the device-path and job-health
# series this PR exists to expose).
_SNAPSHOT_PREFIXES = (
    "janus_engine_",
    "janus_jobs",
    "janus_job_",
    "janus_oldest_",
    "janus_unaggregated_",
    "janus_batches_",
    "janus_task_reports_",
    "janus_report_",
    "janus_span_",
    "janus_ingest_",
    "janus_upload_shed",
    "janus_upload_journal_",
    "janus_database_",
    "janus_datastore_",
    "janus_tx_retries",
    # continuous profiler + device cost ledger + boot timeline (ISSUE 13)
    "janus_profiler_",
    "janus_device_cost_",
    "janus_boot_",
)


def _metrics_snapshot_rider() -> dict:
    """Compact {metric: samples} dict of the engine/job families for
    embedding in the BENCH json."""
    from janus_tpu.metrics import REGISTRY

    snap = REGISTRY.snapshot()
    out = {}
    for name, fam in snap.items():
        if not name.startswith(_SNAPSHOT_PREFIXES):
            continue
        if fam["type"] == "histogram":
            out[name] = [
                {"labels": s["labels"], "sum": round(s["sum"], 6), "count": s["count"]}
                for s in fam["samples"]
            ]
        else:
            out[name] = [
                {"labels": s["labels"], "value": s["value"]} for s in fam["samples"]
            ]
    return out


def _scrape_health_listener(ds=None) -> dict:
    """Boot the real health listener, (optionally) run one health
    sampling pass against `ds`, and scrape /metrics + /statusz over
    HTTP, validating the scrape with the shared exposition parser."""
    import urllib.request

    from janus_tpu.binary_utils import HealthServer
    from janus_tpu.exposition import parse_exposition, validate_exposition

    if ds is not None:
        from janus_tpu.aggregator.health_sampler import HealthSampler

        HealthSampler(ds).run_once()
    srv = HealthServer("127.0.0.1:0").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        errors = validate_exposition(text)
        families, _ = parse_exposition(text)
        with urllib.request.urlopen(base + "/statusz", timeout=10) as resp:
            statusz = json.loads(resp.read())
        # the SLO engine state and the OpenMetrics exemplar mode ride
        # every scrape record (the served phase distils alertz_ok and
        # the exemplar round-trip from these)
        with urllib.request.urlopen(base + "/alertz", timeout=10) as resp:
            alertz = json.loads(resp.read())
        with urllib.request.urlopen(base + "/metrics?openmetrics=1", timeout=10) as resp:
            om_text = resp.read().decode()
        om_errors = validate_exposition(om_text, openmetrics=True)
        with urllib.request.urlopen(base + "/debug/traces?limit=10000", timeout=10) as resp:
            debug_traces = json.loads(resp.read())
        return {
            "base": base,
            "text": text,
            "families": families,
            "errors": errors,
            "statusz": statusz,
            "alertz": alertz,
            "openmetrics_text": om_text,
            "openmetrics_errors": om_errors,
            "debug_traces": debug_traces,
            "server": srv,
        }
    except BaseException:
        srv.stop()
        raise


def _live_trace_ids(traces_doc: dict) -> set:
    """Trace ids currently resolvable on a /debug/traces snapshot."""
    return {s["trace_id"] for s in traces_doc.get("recent", ())} | {
        t["trace_id"] for t in traces_doc.get("slow_traces", ())
    }


def _freshest_resolving_exemplar(exemplars, live_ids) -> tuple:
    """(trace_id, resolved) over parser exemplar dicts, NEWEST first:
    a stale exemplar (a slow request from an earlier phase)
    legitimately outlives the bounded span ring — the claim under test
    is always that a FRESH exemplar resolves. Shared by the served
    phase's roundtrip record and the slo_alert smoke."""
    chosen = None
    for ex in sorted(exemplars, key=lambda e: e.get("ts") or 0, reverse=True):
        tid = ex["labels"].get("trace_id")
        if tid is None:
            continue
        chosen = chosen or tid
        if tid in live_ids:
            return tid, True
    return chosen, False


def _exemplar_roundtrip(scrape: dict) -> dict:
    """Resolve the freshest exemplar of each histogram family in the
    scrape's OpenMetrics text against the same listener's
    /debug/traces snapshot: {checked, resolved, example_trace_id}."""
    from janus_tpu.exposition import parse_exposition

    fams, _ = parse_exposition(scrape["openmetrics_text"], openmetrics=True)
    live_ids = _live_trace_ids(scrape["debug_traces"])
    checked = resolved = 0
    example = None
    for fam in fams.values():
        exemplars = [ex for _, _, ex in fam.exemplars]
        if not any(ex["labels"].get("trace_id") for ex in exemplars):
            continue
        checked += 1
        tid, ok = _freshest_resolving_exemplar(exemplars, live_ids)
        if ok:
            resolved += 1
            example = example or tid
    return {
        "checked": checked,
        "resolved": resolved,
        "example_trace_id": example,
        # at least one exemplar must exist AND resolve once real spans
        # have flowed; a ring-evicted older exemplar is not a failure
        "ok": checked > 0 and resolved > 0,
    }


def _trace_lifecycle_smoke() -> dict:
    """Prove the report-lifecycle tracing tentpole (ISSUE 6) on a live
    loopback leader+helper pair with the two-round fake VDAF: the
    creator persists a trace context in the aggregation job row; a
    driver instance runs the init round; a SECOND, fresh driver
    instance (the in-process analog of a driver restart — no shared
    state beyond the datastore) runs the continue round; a collection
    is created, persisted with its own trace context, and driven to a
    released aggregate. The flight recorder must then show leader
    driver spans and helper handler spans from BOTH rounds sharing the
    persisted job trace id, the collect-finish span linking back to
    it, and non-empty janus_report_e2e_seconds for both stages."""
    import dataclasses

    from janus_tpu import metrics as _m
    from janus_tpu import trace as _tr
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    def _e2e_counts() -> dict:
        fam = _m.REGISTRY.snapshot().get("janus_report_e2e_seconds", {})
        return {
            s["labels"].get("stage"): s["count"] for s in fam.get("samples", ())
        }

    e2e_before = _e2e_counts()
    clock = MockClock(Time(1_600_000_000))
    leader_eph = EphemeralDatastore(clock=clock)
    helper_eph = EphemeralDatastore(clock=clock)
    leader_ds, helper_ds = leader_eph.datastore, helper_eph.datastore
    leader_srv = DapServer(DapHttpApp(Aggregator(leader_ds, clock, Config()))).start()
    helper_srv = DapServer(DapHttpApp(Aggregator(helper_ds, clock, Config()))).start()
    try:
        vdaf = VdafInstance.fake_two_round()
        collector_kp = generate_hpke_config_and_private_key(config_id=200)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=1),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task))
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task))

        http = HttpClient()
        params = ClientParameters(
            leader_task.task_id, leader_srv.url, helper_srv.url, leader_task.time_precision
        )
        client = Client.with_fetched_configs(params, vdaf, http, clock=clock)
        measurements = [1, 0, 1]
        for m in measurements:
            client.upload(m)

        creator = AggregationJobCreator(
            leader_ds, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        )
        assert creator.run_once() == 1
        job = leader_ds.run_tx(
            lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id)
        )[0]
        job_tc = job.trace_context
        job_trace_id = _tr.trace_id_of(job_tc) or ""
        helper_job_tc = None

        # round 1 (init) with one driver instance, round 2 (continue)
        # with a FRESH one: the only way the second can join the first's
        # trace is through the persisted row — the restart story
        jd_cfg = JobDriverConfig(max_concurrent_job_workers=1)
        driver_a = AggregationJobDriver(leader_ds, http)
        assert JobDriver(jd_cfg, driver_a.acquirer(), driver_a.stepper).run_once() == 1
        helper_job = helper_ds.run_tx(
            lambda tx: tx.get_aggregation_job(helper_task.task_id, job.job_id)
        )
        helper_job_tc = helper_job.trace_context if helper_job else None
        driver_b = AggregationJobDriver(leader_ds, http)
        assert JobDriver(jd_cfg, driver_b.acquirer(), driver_b.stepper).run_once() == 1

        # collect end-to-end through the real collector + driver
        start = Time(clock.now().seconds).to_batch_interval_start(
            leader_task.time_precision
        )
        query = Query.time_interval(
            Interval(Time(start.seconds - 3600), Duration(2 * 3600))
        )
        collector = Collector(
            CollectorParameters(
                leader_task.task_id,
                leader_srv.url,
                leader_task.collector_auth_token,
                collector_kp,
            ),
            vdaf,
            http,
        )
        cj_id = collector.start_collection(query)
        cjob = leader_ds.run_tx(
            lambda tx: tx.get_collection_job(leader_task.task_id, cj_id)
        )
        collection_tc = cjob.trace_context if cjob else None
        cdriver = CollectionJobDriver(leader_ds, http)
        assert JobDriver(jd_cfg, cdriver.acquirer(), cdriver.stepper).run_once() == 1
        result = collector.poll_once(cj_id, query)

        # the flight recorder (always armed — nothing was installed)
        rec = _tr.flight_recorder()
        spans = rec.snapshot(recent_limit=rec.capacity)["recent"]
        in_job_trace = {s["name"] for s in spans if s["trace_id"] == job_trace_id}
        finish = next(
            (s for s in reversed(spans) if s["name"] == "driver.collect_finish"), None
        )
        linked = (finish or {}).get("args", {}).get("linked_traces", "")
        e2e_after = _e2e_counts()
        return {
            "collected": result.report_count,
            "aggregate": result.aggregate_result,
            "job_trace_context_persisted": bool(job_tc),
            # the helper's row carries the SAME trace id, adopted off
            # the leader's wire request
            "helper_row_same_trace": bool(
                helper_job_tc and job_trace_id and job_trace_id in helper_job_tc
            ),
            "trace_span_names": sorted(in_job_trace),
            "leader_init_span_in_trace": "driver.http_init" in in_job_trace,
            "leader_continue_span_in_trace": "driver.http_continue" in in_job_trace,
            "helper_init_span_in_trace": "dap.aggregate_init" in in_job_trace,
            "helper_continue_span_in_trace": "dap.aggregate_continue" in in_job_trace,
            "collection_trace_context_persisted": bool(collection_tc),
            "collect_finish_span_in_collection_trace": bool(
                finish
                and collection_tc
                and finish["trace_id"] == _tr.trace_id_of(collection_tc)
            ),
            "collect_links_include_job_trace": bool(job_trace_id) and job_trace_id in linked,
            "e2e_aggregate_delta": e2e_after.get("aggregate", 0)
            - e2e_before.get("aggregate", 0),
            "e2e_collect_delta": e2e_after.get("collect", 0)
            - e2e_before.get("collect", 0),
        }
    finally:
        leader_srv.stop()
        helper_srv.stop()
        leader_eph.cleanup()
        helper_eph.cleanup()


def _slo_alert_smoke() -> dict:
    """Live proof of the SLO burn-rate engine (ISSUE 10) over loopback
    HTTP against real listeners: a failpoint-driven 5xx storm on real
    uploads flips the default upload_availability alert to firing on
    /alertz (burn rates over threshold, firing_since set,
    janus_alert_active=1 in /metrics), a latency exemplar from the
    OpenMetrics scrape resolves against a live /debug/traces capture,
    recovery clears the alert, scripts/debug_bundle.py produces a tar
    whose MANIFEST inventories every captured endpoint, and the default
    scrape stays exemplar-free (bit-compatible)."""
    import pathlib
    import subprocess
    import tarfile
    import tempfile
    import urllib.request

    from janus_tpu import failpoints
    from janus_tpu import metrics as _m
    from janus_tpu import slo as _slo
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.binary_utils import HealthServer
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.exposition import parse_exposition, validate_exposition
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    agg = Aggregator(eph.datastore, clock, Config(ingest_decrypt_workers=2))
    srv = DapServer(DapHttpApp(agg), max_handler_threads=4).start()
    health = HealthServer("127.0.0.1:0").start()
    # the production ladder with every window shrunk 900x: the 1h/5m
    # page rung becomes 4s/0.33s — observable in a CI smoke without
    # forking the shipped definitions
    engine = _slo.install_slo_engine(
        _slo.SloEngineConfig(
            evaluation_interval_s=0.05, window_scale=1.0 / 900, budget_window_s=30.0
        )
    )
    base = f"http://127.0.0.1:{health.port}"
    out: dict = {}
    try:
        vdaf = VdafInstance.count()
        leader_kp = generate_hpke_config_and_private_key(config_id=0)
        helper_kp = generate_hpke_config_and_private_key(config_id=1)
        task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=srv.url,
                helper_aggregator_endpoint=srv.url,
                hpke_keys=(leader_kp,),
                min_batch_size=1,
            )
            .build()
        )
        eph.datastore.run_tx(lambda tx: tx.put_task(task))
        params = ClientParameters(task.task_id, srv.url, srv.url, task.time_precision)
        client = Client(params, vdaf, leader_kp.config, helper_kp.config, clock=clock)
        http = HttpClient()

        def upload_once() -> int:
            report = client.prepare_report(1)
            status, _ = http.put(
                params.upload_uri(),
                report.to_bytes(),
                {"Content-Type": "application/dap-report"},
            )
            return status

        def get_json(path: str) -> dict:
            with urllib.request.urlopen(base + path, timeout=10) as resp:
                return json.loads(resp.read())

        def upload_alerts(doc: dict) -> dict:
            return {
                a["severity"]: a
                for a in doc["alerts"]
                if a["alert"] == "upload_availability"
            }

        # --- healthy baseline: real 201s, no alert ---
        good_statuses = [upload_once() for _ in range(3)]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if upload_alerts(get_json("/alertz")):
                break
            time.sleep(0.05)
        baseline = upload_alerts(get_json("/alertz"))
        out["baseline_statuses"] = good_statuses
        out["baseline_firing"] = sorted(
            s for s, a in baseline.items() if a["state"] == "firing"
        )

        # --- failpoint-driven 5xx storm: the report-write flush fails,
        # so REAL uploads (admitted, decrypted) answer 500 ---
        failpoints.configure("report_writer.flush=error")
        storm_statuses = []
        try:
            deadline = time.monotonic() + 20
            fired = None
            while time.monotonic() < deadline:
                storm_statuses.append(upload_once())
                doc = get_json("/alertz")
                page = upload_alerts(doc).get("page")
                if page and page["state"] == "firing":
                    fired = (doc, page)
                    break
                time.sleep(0.05)
        finally:
            failpoints.clear()
        out["storm_statuses_5xx"] = sum(1 for s in storm_statuses if 500 <= s < 600)
        out["alert_fired"] = fired is not None
        if fired:
            doc, page = fired
            out["burn_rate_long"] = page["burn_rate_long"]
            out["burn_rate_short"] = page["burn_rate_short"]
            out["burn_rate_threshold"] = page["burn_rate_threshold"]
            out["burn_over_threshold"] = (
                page["burn_rate_long"] >= page["burn_rate_threshold"]
                and page["burn_rate_short"] >= page["burn_rate_threshold"]
            )
            out["firing_since_set"] = page["firing_since_unix"] is not None
            out["alertz_firing_list"] = doc["firing"]
            slo_doc = next(
                s for s in doc["slos"] if s["name"] == "upload_availability"
            )
            out["budget_remaining_while_firing"] = slo_doc[
                "error_budget_remaining_ratio"
            ]
            out["evidence_present"] = bool(slo_doc["evidence"])

        # --- janus_alert_active visible in the default /metrics scrape
        # (and the default scrape stays exemplar-free) ---
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            default_text = resp.read().decode()
        fams, _ = parse_exposition(default_text)
        active = fams.get("janus_alert_active")
        out["alert_active_in_metrics"] = any(
            labels.get("alert") == "upload_availability"
            and labels.get("severity") == "page"
            and v == 1.0
            for _, labels, v in (active.samples if active else [])
        )
        # re-reading the default scrape WITH exemplar parsing must find
        # none (a substring test would false-positive on a legal label
        # value containing ' # {')
        leak_fams, _ = parse_exposition(default_text, openmetrics=True)
        out["default_scrape_exemplar_free"] = not any(
            f.exemplars for f in leak_fams.values()
        )
        out["default_scrape_valid"] = not validate_exposition(default_text)

        # --- exemplar round-trip: an upload-route latency exemplar from
        # the OpenMetrics scrape resolves to a live /debug/traces span ---
        with urllib.request.urlopen(
            base + "/metrics?openmetrics=1", timeout=10
        ) as resp:
            om_text = resp.read().decode()
            om_ctype = resp.headers.get("Content-Type", "")
        out["openmetrics_content_type_ok"] = om_ctype.startswith(
            "application/openmetrics-text"
        )
        om_errors = validate_exposition(om_text, openmetrics=True)
        out["openmetrics_scrape_valid"] = not om_errors
        out["openmetrics_errors"] = om_errors[:3]
        om_fams, _ = parse_exposition(om_text, openmetrics=True)
        dur = om_fams.get("janus_http_request_duration_seconds")
        upload_exemplars = [
            ex
            for _, labels, ex in (dur.exemplars if dur else [])
            if labels.get("route") == "upload"
        ]
        out["upload_exemplar_count"] = len(upload_exemplars)
        resolved = False
        exemplar_trace = None
        if upload_exemplars:
            exemplar_trace, resolved = _freshest_resolving_exemplar(
                upload_exemplars,
                _live_trace_ids(get_json("/debug/traces?limit=10000")),
            )
        out["exemplar_trace_id"] = exemplar_trace
        out["exemplar_resolves_in_debug_traces"] = resolved

        # --- recovery: healthy uploads, the windows slide past the
        # storm, the alert clears and the gauge drops to 0 ---
        deadline = time.monotonic() + 20
        cleared = False
        while time.monotonic() < deadline:
            upload_once()
            doc = get_json("/alertz")
            if not any(
                a["state"] == "firing" for a in upload_alerts(doc).values()
            ):
                cleared = True
                break
            time.sleep(0.2)
        out["alert_cleared_after_recovery"] = cleared
        out["alert_active_gauge_after_recovery"] = _m.alert_active.get(
            alert="upload_availability", severity="page"
        )

        # --- one-command incident debug bundle against the live
        # listener: every endpoint captured, MANIFEST inventories them ---
        repo = pathlib.Path(__file__).resolve().parent
        with tempfile.TemporaryDirectory() as td:
            bundle_path = os.path.join(td, "bundle.tar.gz")
            proc = subprocess.run(
                [
                    sys.executable,
                    str(repo / "scripts" / "debug_bundle.py"),
                    "--url",
                    base,
                    "--out",
                    bundle_path,
                ],
                capture_output=True,
                text=True,
                timeout=120,
            )
            out["bundle_rc"] = proc.returncode
            out["bundle_err"] = proc.stderr[-300:] if proc.returncode else ""
            if proc.returncode == 0:
                from janus_tpu.tools.debug_bundle import ENDPOINTS

                with tarfile.open(bundle_path) as tar:
                    names = tar.getnames()
                    manifest_name = next(
                        n for n in names if n.endswith("MANIFEST.json")
                    )
                    manifest = json.loads(
                        tar.extractfile(manifest_name).read()
                    )
                target = next(iter(manifest["targets"].values()))
                captured = target["endpoints"]
                out["bundle_endpoints_captured"] = sorted(captured)
                out["bundle_manifest_complete"] = all(
                    name in captured and captured[name].get("status") is not None
                    for name, _ in ENDPOINTS
                )
                out["bundle_files"] = len(manifest["files"])
        return out
    finally:
        _slo.uninstall_slo_engine()
        health.stop()
        srv.stop()
        eph.cleanup()


def _observability_smoke() -> dict:
    """Drive the full observability surface on CPU and prove the
    acceptance criteria end-to-end: the live health listener's /metrics
    scrape is exposition-valid (including a hostile label value
    containing a double quote and a newline), janus_engine_dispatch_seconds
    and janus_jobs carry non-zero samples, /statusz renders task +
    engine-cache state, POST /debug/profile yields a loadable host
    Chrome trace while a concurrent capture 409s, and
    scripts/scrape_check.py passes against the same listener."""
    import pathlib
    import subprocess
    import threading
    import urllib.error
    import urllib.request

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator.engine_cache import engine_cache
    from janus_tpu.datastore.models import (
        AggregationJobModel,
        AggregationJobState,
        LeaderStoredReport,
    )
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import (
        AggregationJobId,
        Duration,
        HpkeCiphertext,
        HpkeConfigId,
        Interval,
        ReportId,
        Role,
        Time,
    )
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    # the continuous profiler runs through the whole smoke like in the
    # real binaries (janus_main installs it by default) — scrape_check
    # below validates /debug/profile live, which requires the sampler
    # running; a fast-ish rate so the short smoke accumulates samples
    from janus_tpu import profiler as _prof

    _prof.install_profiler(_prof.ProfilerConfig(hz=47.0, window_secs=10.0))

    # the telemetry flight recorder likewise runs like in the real
    # binaries — scrape_check validates the /statusz flight section and
    # its last-snapshot freshness against this listener
    from janus_tpu import flight_recorder as _flight

    _flight.install_flight_recorder(
        _flight.FlightRecorderConfig(interval_s=0.5)
    ).snapshot_once()

    # the report-lifecycle tracing smoke runs FIRST so its e2e series
    # and flight-recorder state are live in the scrape below
    trace_lifecycle = _trace_lifecycle_smoke()

    # the SLO burn-rate engine's live proof (ISSUE 10): 5xx storm ->
    # /alertz firing -> exemplar round-trip -> recovery -> debug bundle
    slo_alert = _slo_alert_smoke()

    # a label value that would corrupt an unescaped scrape
    _m.aggregate_step_failure_counter.add(type='hostile"label\nvalue\\end')

    eph = EphemeralDatastore()
    clock = eph.clock
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER)
        .with_(min_batch_size=1)
        .build()
    )

    def provision(tx):
        tx.put_task(task)
        # one in-progress job and one unaggregated report so the
        # sampler has a real backlog to export
        tx.put_aggregation_job(
            AggregationJobModel(
                task.task_id,
                AggregationJobId(b"\x01" * 16),
                b"",
                b"",
                Interval(Time(clock.now().seconds - 120), Duration(60)),
                AggregationJobState.IN_PROGRESS,
                0,
                None,
            )
        )
        tx.put_client_report(
            LeaderStoredReport(
                task.task_id,
                ReportId(b"\x02" * 16),
                Time(clock.now().seconds - 300),
                b"",
                b"share",
                HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
            )
        )
        # book the hand-provisioned report so the conservation ledger's
        # books balance (the real admission path does this in-tx)
        from janus_tpu import ledger as _lg

        _lg.count_admitted(tx, task.task_id, 1)

    eph.datastore.run_tx(provision)
    # engine-cache state for /statusz (hit + miss counters ride along);
    # the dispatch histograms were already fed by the OOM smoke's real
    # engine calls through the span->metric bridge
    inst = VdafInstance.sum_vec(length=4, bits=2)
    engine_cache(inst, bytes(range(16)))
    engine_cache(inst, bytes(range(16)))

    # the task list section janus_main registers in the real binaries
    from janus_tpu.metrics import task_id_label
    from janus_tpu.statusz import register_status_provider

    register_status_provider(
        "tasks",
        lambda: [
            {
                "task_id": task_id_label(t.task_id.data),
                "role": t.role.name,
                "vdaf": t.vdaf.kind,
            }
            for t in eph.datastore.run_tx(lambda tx: tx.get_tasks(), "statusz_tasks")
        ],
    )

    # the report-flow conservation ledger runs like in the real binaries
    # (every datastore-owning binary installs it) — scrape_check below
    # validates the `ledger` statusz section and /debug/ledger live; one
    # evaluation before the scrape so the balance document is populated
    from janus_tpu import ledger as _ledger

    ledger_ev = _ledger.install_ledger(eph.datastore, _ledger.LedgerConfig())
    ledger_ev.evaluate_once()

    scrape = _scrape_health_listener(ds=eph.datastore)
    srv = scrape["server"]
    try:
        base = scrape["base"]
        families = scrape["families"]
        dispatch = families.get("janus_engine_dispatch_seconds")
        dispatch_count = sum(
            v
            for name, labels, v in (dispatch.samples if dispatch else [])
            if name.endswith("_count")
        )
        jobs = families.get("janus_jobs")
        jobs_in_progress = next(
            (
                v
                for name, labels, v in (jobs.samples if jobs else [])
                if labels.get("type") == "aggregation"
                and labels.get("state") == "in_progress"
            ),
            0.0,
        )
        hostile = families["janus_aggregate_step_failures"]
        hostile_ok = any(
            labels.get("type") == 'hostile"label\nvalue\\end'
            for _, labels, _ in hostile.samples
        )
        statusz = scrape["statusz"]

        # concurrent profile captures: exactly one wins, one 409s. The
        # listener is in-process, so the second POST fires only once
        # the first's capture window is provably open (the guard lock
        # is held) — deterministic, not a sleep race.
        import janus_tpu.binary_utils as _bu

        codes = []

        def post(seconds):
            req = urllib.request.Request(
                base + f"/debug/profile?seconds={seconds}", method="POST"
            )
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    codes.append((resp.status, resp.read()))
            except urllib.error.HTTPError as e:
                codes.append((e.code, e.read()))
            except Exception as e:  # record, never drop silently
                codes.append((f"error: {type(e).__name__}: {e}", b""))

        t1 = threading.Thread(target=post, args=(2,))
        t1.start()
        deadline = time.monotonic() + 60
        while not _bu._profile_lock.locked() and time.monotonic() < deadline:
            time.sleep(0.02)
        t2 = threading.Thread(target=post, args=(1,))
        t2.start()
        t1.join()
        t2.join()
        status_codes = sorted((c for c, _ in codes), key=str)
        host_trace_loadable = False
        for code, body in codes:
            if code == 200:
                artifacts = json.loads(body)
                raw = open(artifacts["host_chrome_trace"]).read().rstrip()
                json.loads(raw if raw.endswith("]") else raw + "{}]")
                host_trace_loadable = True

        # the always-on flight recorder over live HTTP: /debug/traces
        # must be valid JSON with the lifecycle smoke's spans in it
        with urllib.request.urlopen(base + "/debug/traces?limit=50", timeout=10) as resp:
            traces_doc = json.loads(resp.read())
        debug_traces_ok = (
            {"recent", "slow_traces", "digests", "recorded_total"} <= set(traces_doc)
            and traces_doc["recorded_total"] > 0
            and len(traces_doc["recent"]) > 0
        )

        # continuous profiler over live HTTP (ISSUE 13): the collapsed
        # document folds clean (shared validator) and the JSON mode
        # carries per-role shares with the sampler enabled
        with urllib.request.urlopen(base + "/debug/profile", timeout=10) as resp:
            collapsed_text = resp.read().decode()
        profile_collapsed_ok = (
            not _prof.validate_collapsed(collapsed_text) and bool(collapsed_text)
        )
        with urllib.request.urlopen(
            base + "/debug/profile?format=json", timeout=10
        ) as resp:
            profile_doc = json.loads(resp.read())
        profile_roles = sorted(profile_doc.get("roles", {}))
        with urllib.request.urlopen(base + "/debug/boot", timeout=10) as resp:
            boot_doc = json.loads(resp.read())
        debug_boot_ok = {"started_unix", "ready", "phases"} <= set(boot_doc)

        # conservation ledger over live HTTP (ISSUE 20): /debug/ledger
        # must answer the full balance document with the smoke's one
        # admitted-but-unaggregated report attributably in flight
        with urllib.request.urlopen(base + "/debug/ledger", timeout=10) as resp:
            ledger_doc = json.loads(resp.read())
        debug_ledger_ok = (
            ledger_doc.get("enabled") is True
            and {"evaluations", "tasks", "breaches"} <= set(ledger_doc)
            and ledger_doc["evaluations"] >= 1
        )

        repo = pathlib.Path(__file__).resolve().parent
        check = subprocess.run(
            [
                sys.executable,
                str(repo / "scripts" / "scrape_check.py"),
                "--url",
                base,
                "--statusz",
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        return {
            "scrape_valid": not scrape["errors"],
            "scrape_errors": scrape["errors"][:5],
            "engine_dispatch_samples": int(dispatch_count),
            "jobs_in_progress": jobs_in_progress,
            "hostile_label_roundtrip": hostile_ok,
            "statusz_tasks": len(statusz.get("tasks", [])),
            "statusz_engine_cache_entries": statusz.get("engine_cache", {}).get(
                "entries", 0
            ),
            "statusz_job_health_present": "job_health" in statusz,
            "oldest_unaggregated_age_s": statusz.get("job_health", {})
            .get("oldest_unaggregated_report_age_seconds", {}),
            "profile_status_codes": status_codes,
            "profile_host_trace_loadable": host_trace_loadable,
            "debug_traces_ok": debug_traces_ok,
            "statusz_flight_recorder_present": "flight_recorder" in statusz,
            "statusz_flight_present": "flight" in statusz,
            "scrape_check_rc": check.returncode,
            "scrape_check_err": check.stderr[-500:] if check.returncode else "",
            # continuous profiler over live HTTP (ISSUE 13): collapsed
            # format well-formed, JSON roles present, statusz sections
            "profile_collapsed_ok": profile_collapsed_ok,
            "profile_roles": profile_roles,
            "debug_boot_ok": debug_boot_ok,
            "statusz_profile_present": "profile" in statusz,
            "statusz_device_cost_present": "device_cost" in statusz,
            # conservation ledger (ISSUE 20): statusz section + live
            # /debug/ledger document, books balanced on the smoke task
            "statusz_ledger_present": "ledger" in statusz,
            "debug_ledger_ok": debug_ledger_ok,
            "ledger_breaches": ledger_doc.get("breaches", []),
            "trace_lifecycle": trace_lifecycle,
            "slo_alert": slo_alert,
        }
    finally:
        srv.stop()
        eph.cleanup()
        _ledger.uninstall_ledger()
        _flight.uninstall_flight_recorder()
        _prof.uninstall_profiler()


def _ledger_smoke() -> dict:
    """Smoke-level proof of the report-flow conservation ledger (ISSUE
    20): reports admitted through the REAL group-commit admission path
    leave the books balanced (ingest imbalance 0); then the
    `ledger.drop_report` failpoint silently deletes one admitted report
    AFTER its admission tx counted it — no rate metric moves, but the
    very next ledger evaluation books a +1 ingest imbalance, the breach
    fires immediately (grace 0), and the `conservation` SLO signal goes
    bad on the same tick."""
    from janus_tpu import failpoints as _fp
    from janus_tpu import ledger as _ledger
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.datastore.models import LeaderStoredReport
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import (
        HpkeCiphertext,
        HpkeConfigId,
        ReportId,
        Role,
        Time,
    )
    from janus_tpu.slo import ConservationSignal
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    eph = EphemeralDatastore()
    try:
        ds = eph.datastore
        clock = eph.clock
        task = (
            TaskBuilder(
                QueryTypeConfig.time_interval(), VdafInstance.count(), Role.LEADER
            )
            .with_(min_batch_size=1)
            .build()
        )
        ds.run_tx(lambda tx: tx.put_task(task))
        batcher = ReportWriteBatcher(ds)

        def mk(i: int) -> LeaderStoredReport:
            return LeaderStoredReport(
                task.task_id,
                ReportId(bytes([i]) * 16),
                Time(clock.now().seconds - 60),
                b"",
                b"share",
                HpkeCiphertext(HpkeConfigId(0), b"enc", b"payload"),
            )

        batcher.flush_direct([mk(i) for i in range(1, 4)])
        # grace 0: a nonzero imbalance breaches on the evaluation that
        # first sees it — "within one sampler interval" by construction
        ev = _ledger.LedgerEvaluator(ds, _ledger.LedgerConfig(grace_s=0.0))
        ev.evaluate_once()
        doc = ev.document()
        balanced_ok = bool(doc["tasks"]) and all(
            t["imbalance"].get("ingest") == 0 and t["imbalance"].get("collect") == 0
            for t in doc["tasks"].values()
        )
        balanced_breaches = list(doc.get("breaches", []))

        # fresh SLO tick state for the conservation signal (the real
        # engine holds this per-signal dict; a stub suffices here)
        class _Eng:
            _condition_state: dict = {}

        eng = _Eng()
        sig = ConservationSignal()
        bad0, total0, _ = sig.read(eng)

        # injected-loss lane: the admission tx counts the report, the
        # failpoint deletes the row before commit — a silent loss
        _fp.configure("ledger.drop_report=error:1.0,count=1")
        try:
            batcher.flush_direct([mk(9)])
        finally:
            _fp.clear()
        ev.evaluate_once()
        doc2 = ev.document()
        loss_imbalances = {
            label: t["imbalance"].get("ingest")
            for label, t in doc2["tasks"].items()
        }
        bad1, total1, _ = sig.read(eng)
        return {
            "balanced_ok": balanced_ok,
            "balanced_breaches": balanced_breaches,
            "loss_imbalance_total": sum(v or 0 for v in loss_imbalances.values()),
            "loss_detected_in_one_evaluation": any(
                v == 1 for v in loss_imbalances.values()
            ),
            "breach_fired": bool(doc2.get("breaches")),
            "slo_bad_before": bad0,
            "slo_bad_after": bad1,
            "slo_fired": bad1 > bad0 and total1 > total0,
            "evaluations": doc2.get("evaluations", 0),
        }
    finally:
        eph.cleanup()


def _failpoint_overhead(iters: int = 200_000) -> dict:
    """Measure — not assume — the cost of an instrumented failpoint
    site on the hot path: ns per `failpoints.hit()` with the registry
    disarmed (the production state: one module-flag check) and with
    OTHER failpoints armed (one dict miss under the registry lock),
    against an empty-loop baseline. The upload/commit/dispatch paths
    each carry one or two of these per operation, so disarmed cost must
    be unmeasurable against any real work."""
    import time as _time

    from janus_tpu import failpoints

    was = failpoints.status()
    failpoints.clear()

    def measure(fn) -> float:
        t0 = _time.perf_counter()
        for _ in range(iters):
            fn()
        return (_time.perf_counter() - t0) / iters * 1e9

    try:
        baseline_ns = measure(lambda: None)
        disabled_ns = measure(lambda: failpoints.hit("bench.hot_path"))
        failpoints.configure("bench.other_site=delay:0.0,count=0")
        armed_other_ns = measure(lambda: failpoints.hit("bench.hot_path"))
    finally:
        failpoints.clear()
        if was.get("enabled"):  # restore a caller's armed schedule
            failpoints.configure(
                {
                    n: f"{fp['action']}:{fp['arg']},prob={fp['prob']}"
                    + (f",count={fp['count']}" if fp["count"] is not None else "")
                    for n, fp in was["failpoints"].items()
                }
            )
    return {
        "iters": iters,
        "baseline_ns": round(baseline_ns, 1),
        "disabled_ns_per_hit": round(disabled_ns, 1),
        "armed_other_ns_per_hit": round(armed_other_ns, 1),
        "disabled_overhead_ns": round(disabled_ns - baseline_ns, 1),
    }


def _paired_ratio(slow_fn, fast_fn, iters: int = 15):
    """(min slow s, min fast s, median per-pair ratio). Measures in
    INTERLEAVED pairs with GC paused and takes the median per-pair
    ratio: the two paths must see the same CPU frequency / cache /
    scheduler conditions, or whole-run drift lands on one side and an
    acceptance gate flakes (observed on the codec bench: a 4.9x
    outlier from separate-block best-of-N against a 6.5x steady
    state). Shared by the codec record and the upload-batch record."""
    import gc
    import statistics
    import time as _time

    def timed(fn) -> float:
        t0 = _time.perf_counter()
        fn()
        return _time.perf_counter() - t0

    slow_ts, fast_ts, ratios = [], [], []
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        timed(slow_fn), timed(fast_fn)  # warm first-touch pages
        for _ in range(iters):
            s = timed(slow_fn)
            f = timed(fast_fn)
            slow_ts.append(s)
            fast_ts.append(f)
            ratios.append(s / f)
    finally:
        if gc_was_enabled:
            gc.enable()
    return min(slow_ts), min(fast_ts), statistics.median(ratios)


def _codec_speed_record(inst=None, batch: int = 2048) -> dict:
    """Measured leader<->helper wire-codec speed (ISSUE 9 acceptance:
    columnar >= 5x the per-report loop at batch >= 1024, bit-identical
    bytes). Builds a prepare-shaped init request two ways — the
    pre-ISSUE-9 per-report loop (encode_field_rows rows ->
    encode_prep_share_raw -> encode_pingpong -> PrepareInit dataclasses
    -> items encode) and the columnar path (one vectorized framing pass
    + PreEncoded splices) — asserts the request bytes are IDENTICAL,
    and times both; the response side (AggregationJobResp.from_bytes vs
    decode_prepare_resps_fast) rides along."""
    import secrets
    import time as _time

    import numpy as np

    from janus_tpu.messages import (
        AggregationJobInitializeReq,
        AggregationJobResp,
        HpkeCiphertext,
        HpkeConfigId,
        PartialBatchSelector,
        PreEncoded,
        PrepareInit,
        PrepareResp,
        PrepareStepResult,
        ReportId,
        ReportMetadata,
        ReportShare,
        Time,
        decode_prepare_resps_fast,
        encode_report_share_raw,
    )
    from janus_tpu.vdaf.registry import VdafInstance, circuit_for
    from janus_tpu.vdaf.wire import (
        PP_FINISH,
        PP_INITIALIZE,
        Prio3Wire,
        encode_field_rows,
        encode_pingpong,
        encode_pingpong_share_column,
    )

    if inst is None or inst.kind == "poplar1":
        inst = VdafInstance.histogram(10)
    circ = circuit_for(inst)
    wire = Prio3Wire(circ)

    class _JF:
        LIMBS = circ.FIELD.ENCODED_SIZE // 8
        MODULUS = circ.FIELD.MODULUS

    jf = _JF()
    rng = np.random.default_rng(0xC0DEC)
    n = batch
    v = circ.verifier_len
    ver0 = tuple(
        rng.integers(0, 1 << 31, size=(n, v), dtype=np.uint64)
        for _ in range(jf.LIMBS)
    )
    part0 = (
        rng.integers(0, 1 << 63, size=(n, 2), dtype=np.uint64)
        if wire.uses_jr
        else None
    )
    # stored-report columns shared by both paths (the driver reads
    # these from the datastore rows)
    rids = [secrets.token_bytes(16) for _ in range(n)]
    t = Time(1_600_000_000)
    pub = secrets.token_bytes(wire.public_share_len)
    ct = HpkeCiphertext(
        HpkeConfigId(1),
        secrets.token_bytes(32),
        secrets.token_bytes(wire.helper_share_len + 44),
    )
    pbs = PartialBatchSelector.time_interval()

    def loop_path() -> bytes:
        ver_rows = encode_field_rows(jf, ver0)
        part_rows = (
            [row.tobytes() for row in np.asarray(part0, dtype="<u8")]
            if wire.uses_jr
            else [None] * n
        )
        prep_inits = []
        for i in range(n):
            prep_share = wire.encode_prep_share_raw(ver_rows[i], part_rows[i])
            prep_inits.append(
                PrepareInit(
                    ReportShare(ReportMetadata(ReportId(rids[i]), t), pub, ct),
                    encode_pingpong(PP_INITIALIZE, None, prep_share),
                )
            )
        return AggregationJobInitializeReq(b"", pbs, tuple(prep_inits)).to_bytes()

    def columnar_path() -> bytes:
        frames = encode_pingpong_share_column(jf, ver0, part0)
        items = tuple(
            PreEncoded(
                encode_report_share_raw(rids[i], t.seconds, pub, ct) + frames.row(i)
            )
            for i in range(n)
        )
        return AggregationJobInitializeReq(b"", pbs, items).to_bytes()

    identical = loop_path() == columnar_path()

    enc_loop_s, enc_col_s, enc_ratio = _paired_ratio(loop_path, columnar_path)

    # response side: the helper's typical 1-round answer per report
    msg = encode_pingpong(PP_FINISH, b"x" * 16, None)
    body = AggregationJobResp(
        tuple(
            PrepareResp(ReportId(r), PrepareStepResult.cont(msg)) for r in rids
        )
    ).to_bytes()
    dec_loop_s, dec_col_s, dec_ratio = _paired_ratio(
        lambda: AggregationJobResp.from_bytes(body),
        lambda: decode_prepare_resps_fast(body),
    )
    # content equivalence, not just count: the record's claim must be
    # the one tests/test_wire_columnar.py pins
    ref = AggregationJobResp.from_bytes(body)
    col = decode_prepare_resps_fast(body)
    decoded_identical = (
        col.report_ids == [r.report_id.data for r in ref.prepare_resps]
        and list(col.kinds) == [r.result.kind for r in ref.prepare_resps]
        and col.messages == [r.result.message for r in ref.prepare_resps]
        and col.errors == [r.result.prepare_error for r in ref.prepare_resps]
    )

    return {
        "vdaf": inst.kind,
        "batch": n,
        "wire_bytes_identical": identical,
        "decode_roundtrip_ok": decoded_identical,
        "encode_us_per_report_loop": round(enc_loop_s / n * 1e6, 3),
        "encode_us_per_report_columnar": round(enc_col_s / n * 1e6, 3),
        "encode_speedup": round(enc_ratio, 2),
        "decode_us_per_report_loop": round(dec_loop_s / n * 1e6, 3),
        "decode_us_per_report_columnar": round(dec_col_s / n * 1e6, 3),
        "decode_speedup": round(dec_ratio, 2),
    }


def _hist_totals(metric) -> tuple[int, float]:
    """(observation count, sum) across every label set of a Histogram
    (delta-based batching evidence for the ingest-batch records)."""
    with metric._lock:
        return sum(metric._totals.values()), sum(metric._sums.values())


def _upload_client_stack(cfg=None, inst=None, max_handler_threads: int = 24):
    """A served upload stack on loopback HTTP (leader Aggregator +
    DapServer + a Client for one provisioned task), shared by the
    ingest-batch smoke and the open-loop load generator. Returns
    (eph, srv, task, params, client, clock)."""
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.messages import Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    clock = MockClock(Time(1_600_000_000))
    eph = EphemeralDatastore(clock=clock)
    agg = Aggregator(eph.datastore, clock, cfg or Config())
    srv = DapServer(DapHttpApp(agg), max_handler_threads=max_handler_threads).start()
    vdaf = inst or VdafInstance.count()
    leader_kp = generate_hpke_config_and_private_key(config_id=0)
    helper_kp = generate_hpke_config_and_private_key(config_id=1)
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
        .with_(
            leader_aggregator_endpoint=srv.url,
            helper_aggregator_endpoint=srv.url,
            hpke_keys=(leader_kp,),
            min_batch_size=1,
        )
        .build()
    )
    eph.datastore.run_tx(lambda tx: tx.put_task(task))
    params = ClientParameters(task.task_id, srv.url, srv.url, task.time_precision)
    client = Client(params, vdaf, leader_kp.config, helper_kp.config, clock=clock)
    return eph, srv, task, params, client, clock


def _upload_batch_speed_record(inst=None, window: int = 256) -> dict:
    """Measured server-side upload decrypt+decode speed (ISSUE 11
    acceptance: batched >= 3x the per-report path at window >= 256,
    bit-identical results). Runs the same window of REAL client upload
    bodies two ways — the per-report oracle (Report.from_bytes ->
    upload_prepare -> upload_decrypt_validate, exactly what the
    pre-batching decrypt pool executed per report) and the batched
    path (decode_reports_fast -> upload_prepare_columns ->
    upload_decrypt_validate_batch) — asserts the stored reports are
    IDENTICAL, and times both interleaved (median per-pair ratio, GC
    paused; the codec bench's anti-drift discipline)."""
    import numpy as np

    from janus_tpu.aggregator import Config
    from janus_tpu.aggregator.core import TaskAggregator
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.time_util import MockClock
    from janus_tpu.messages import Report, Role, Time, decode_reports_fast
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import random_measurements

    if inst is None or inst.kind == "poplar1":
        inst = VdafInstance.count()
    clock = MockClock(Time(1_600_000_000))
    leader_kp = generate_hpke_config_and_private_key(config_id=0)
    helper_kp = generate_hpke_config_and_private_key(config_id=1)
    task = (
        TaskBuilder(QueryTypeConfig.time_interval(), inst, Role.LEADER)
        .with_(
            leader_aggregator_endpoint="http://leader",
            helper_aggregator_endpoint="http://helper",
            hpke_keys=(leader_kp,),
            min_batch_size=1,
        )
        .build()
    )
    params = ClientParameters(
        task.task_id, "http://leader", "http://helper", task.time_precision
    )
    client = Client(params, inst, leader_kp.config, helper_kp.config, clock=clock)
    rng = np.random.default_rng(0xB47C4)
    meas = random_measurements(inst, window, rng)
    bodies = [
        client.prepare_report(
            m.tolist() if getattr(m, "ndim", 0) else int(m)
        ).to_bytes()
        for m in meas
    ]
    ta = TaskAggregator(task, Config())

    def per_report():
        out = []
        for b in bodies:
            r = Report.from_bytes(b)
            kp = ta.upload_prepare(clock, r)
            out.append(ta.upload_decrypt_validate(r, kp))
        return out

    idxs = list(range(len(bodies)))

    def batched():
        col = decode_reports_fast(bodies)
        kps = ta.upload_prepare_columns(clock, col, idxs)
        return ta.upload_decrypt_validate_batch(col, idxs, kps[0])

    identical = per_report() == batched()
    slow_s, fast_s, ratio = _paired_ratio(per_report, batched, iters=9)
    return {
        "vdaf": inst.kind,
        "window": window,
        "stored_reports_identical": identical,
        "per_report_us_per_report": round(slow_s / window * 1e6, 2),
        "batched_us_per_report": round(fast_s / window * 1e6, 2),
        "per_report_rps": round(window / slow_s, 1),
        "batched_rps": round(window / fast_s, 1),
        "speedup": round(ratio, 2),
    }


def _ingest_batch_smoke() -> dict:
    """Batched-ingest smoke (ISSUE 11): a real loopback HTTP burst
    through the window-batched decode/decrypt stages — 12 valid
    uploads, 1 with a tampered leader ciphertext, 3 undecodable bodies
    — must answer EXACTLY 12x201 + 4x400 with the 12 committed exactly
    once (a replayed PUT stays 201 and adds no row); a direct
    pipeline feed then proves the windowing deterministically (8
    submits inside one linger -> ONE hpke_open_batch call of 8
    lanes)."""
    import dataclasses
    from concurrent.futures import ThreadPoolExecutor

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator import Config
    from janus_tpu.aggregator.core import TaskAggregator
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.datastore.store import EphemeralDatastore
    from janus_tpu.ingest import IngestPipeline

    cfg = Config(ingest_batch_linger_ms=40.0)
    eph, srv, task, params, client, clock = _upload_client_stack(
        cfg, max_handler_threads=24
    )
    try:
        reports = [client.prepare_report(1) for _ in range(13)]
        tampered = dataclasses.replace(
            reports[12],
            leader_encrypted_input_share=dataclasses.replace(
                reports[12].leader_encrypted_input_share,
                payload=bytes(
                    [reports[12].leader_encrypted_input_share.payload[0] ^ 1]
                )
                + reports[12].leader_encrypted_input_share.payload[1:],
            ),
        )
        bodies = [r.to_bytes() for r in reports[:12]]
        burst = bodies + [tampered.to_bytes()] + [b"not-a-dap-report"] * 3

        def put(body):
            http = HttpClient()
            return http.put(
                params.upload_uri(), body, {"Content-Type": "application/dap-report"}
            )[0]

        calls0, lanes0 = _hist_totals(_m.hpke_batch_size)
        with ThreadPoolExecutor(max_workers=len(burst)) as pool:
            statuses = list(pool.map(put, burst))
        http_calls, http_lanes = _hist_totals(_m.hpke_batch_size)
        http_calls -= calls0
        http_lanes -= lanes0
        replay_status = put(bodies[0])  # exactly-once: replays stay 201
        stored, _ = eph.datastore.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )

        # windowing proof: 8 back-to-back submits (microseconds of
        # work) against a 2 s linger — the decode worker drains them
        # into one window and returns the moment the 8th arrives, so
        # the linger costs nothing in the good case and only a >2 s
        # scheduler stall between two queue puts could split the
        # window (tier-1 pins direct_batch_calls == 1 on this)
        eph2 = EphemeralDatastore(clock=clock)
        try:
            eph2.datastore.run_tx(lambda tx: tx.put_task(task))
            ta = TaskAggregator(task, cfg)
            writer = ReportWriteBatcher(eph2.datastore, 100, 0)
            pipe = IngestPipeline(
                writer, queue_depth=16, batch_window=8, batch_linger_ms=2000.0
            )
            try:
                calls0, lanes0 = _hist_totals(_m.hpke_batch_size)
                tickets = [pipe.submit(ta, clock, b) for b in bodies[:8]]
                ok = all(t.result(timeout_s=60) for t in tickets)
                calls1, lanes1 = _hist_totals(_m.hpke_batch_size)
            finally:
                pipe.close()
                writer.close()
        finally:
            eph2.cleanup()
        batch_secs_count, _ = _hist_totals(_m.ingest_decrypt_batch_seconds)
        return {
            "accepted": statuses.count(201),
            "rejected_4xx": sum(1 for s in statuses if 400 <= s < 500),
            "statuses_other": sorted(
                {s for s in statuses if s != 201 and not 400 <= s < 500}
            ),
            "stored_reports": int(stored),
            "committed_exactly_once": int(stored) == statuses.count(201),
            "replay_still_201": replay_status == 201,
            # batching evidence over HTTP (informational: arrival
            # clustering depends on host load) and the deterministic
            # direct-feed proof (asserted by test_bench_dry_run_smoke)
            "http_batch_calls": int(http_calls),
            "http_batched_reports": int(http_lanes),
            "direct_feed_ok": bool(ok),
            "direct_batch_calls": int(calls1 - calls0),
            "direct_batch_lanes": int(lanes1 - lanes0),
            "decrypt_batch_seconds_sampled": batch_secs_count > 0,
        }
    finally:
        srv.stop()
        eph.cleanup()


def _open_loop_upload_record(
    duration_s: float = 3.0,
    capacity_rps: float = 120.0,
    rate_factor: float = 2.0,
) -> dict:
    """Open-loop (coordinated-omission-free) upload load generator
    (ISSUE 11): arrivals on a FIXED schedule at `rate_factor`x the
    configured admission capacity, each request's latency measured
    from its INTENDED send time — a stalled server accumulates
    lateness into the recorded tail instead of silently slowing the
    generator down (the classic closed-loop bench lie). The stack is
    given a token-bucket capacity (`capacity_rps`) so sustained
    overload is a deterministic condition, not a host-speed accident:
    ~half the offered load must shed 429 while admitted uploads'
    p50/p99-under-overload and the exact shed split become tracked
    BENCH numbers."""
    import threading
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator import Config
    from janus_tpu.core.http_client import HttpClient

    cfg = Config(
        ingest_batch_linger_ms=5.0,
        upload_bucket_rate=capacity_rps,
        upload_bucket_burst=max(8, int(capacity_rps / 4)),
    )
    eph, srv, task, params, client, clock = _upload_client_stack(
        cfg, max_handler_threads=32
    )
    try:
        hdrs = {"Content-Type": "application/dap-report"}
        offered_rps = capacity_rps * rate_factor
        n = min(1500, max(30, int(offered_rps * duration_s)))
        bodies = [client.prepare_report(1).to_bytes() for _ in range(n)]

        local = threading.local()

        def get_http() -> HttpClient:
            h = getattr(local, "http", None)
            if h is None:
                h = local.http = HttpClient()
            return h

        start = _time.perf_counter() + 0.2
        results = []
        lock = threading.Lock()

        def fire(k: int, body: bytes) -> None:
            intended = start + k / offered_rps
            now = _time.perf_counter()
            if intended > now:
                _time.sleep(intended - now)
            t_begin = _time.perf_counter()
            try:
                status, _body = get_http().put(params.upload_uri(), body, hdrs)
            except Exception:
                status = -1
            done = _time.perf_counter()
            with lock:
                # latency FROM INTENDED send: queueing in the generator
                # (all workers busy) and in the server both count
                results.append((status, done - intended, t_begin - intended))

        shed0 = _m.upload_shed_counter.total()
        with ThreadPoolExecutor(max_workers=48) as pool:
            for k, body in enumerate(bodies):
                pool.submit(fire, k, body)
        wall = _time.perf_counter() - start
        shed_delta = _m.upload_shed_counter.total() - shed0

        def pctl(vals, q):
            if not vals:
                return None
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(q * len(vals)))]

        lat_ok = [lat for s, lat, _ in results if s == 201]
        lat_all = [lat for s, lat, _ in results if s > 0]
        lag = [b for _, _, b in results]
        n201 = sum(1 for s, _, _ in results if s == 201)
        n429 = sum(1 for s, _, _ in results if s == 429)
        return {
            "capacity_rps_configured": capacity_rps,
            "offered_rps": round(offered_rps, 1),
            "requests": len(results),
            "duration_s": round(wall, 2),
            "accepted_201": n201,
            "shed_429": n429,
            "errors": sum(1 for s, _, _ in results if s not in (201, 429) ),
            "served_rps": round(n201 / wall, 1) if wall > 0 else None,
            "shed_accounted": shed_delta == n429,
            # the tracked overload numbers: latency measured from the
            # intended (scheduled) send instant
            "p50_ms_201": round(pctl(lat_ok, 0.50) * 1000, 1) if lat_ok else None,
            "p99_ms_201": round(pctl(lat_ok, 0.99) * 1000, 1) if lat_ok else None,
            "p50_ms_all": round(pctl(lat_all, 0.50) * 1000, 1) if lat_all else None,
            "p99_ms_all": round(pctl(lat_all, 0.99) * 1000, 1) if lat_all else None,
            # generator honesty: how late requests LEFT the generator
            # relative to their schedule (large = the generator itself
            # could not offer the load; the lateness is still charged
            # to the recorded latencies above, never hidden)
            "start_lag_p99_ms": round(pctl(lag, 0.99) * 1000, 1) if lag else None,
        }
    finally:
        srv.stop()
        eph.cleanup()


def _pipeline_smoke() -> dict:
    """Stage-pipeline overlap smoke (scripts/chaos_run.py --scenario
    pipeline --smoke): the REAL driver binary with the pipelined
    stepper (the default) steps many small jobs against a loopback
    helper whose RTT is stretched by a delay failpoint; the smoke
    asserts overlap actually happened — the device lane was busy while
    an HTTP leg was in flight (janus_step_pipeline_overlap_total > 0,
    overlap ratio > 0 recorded), stage metrics populated, SIGTERM
    drain clean, and the final collection exactly equals the admitted
    ground truth."""
    return _run_chaos_subprocess(
        ["--scenario", "pipeline", "--smoke", "--json"], timeout=300
    )


def _run_chaos_subprocess(extra_args: list, timeout: float) -> dict:
    """Run scripts/chaos_run.py with `extra_args` and return its JSON
    record. A hung/garbled/failed harness degrades to an ok:false
    record — the dry run always emits its JSON line (the BENCH rc:124
    lesson), and test_bench_dry_run_smoke reports THAT dict instead of
    an opaque traceback."""
    import pathlib
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single-device, like the real drivers
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join("scripts", "chaos_run.py"), *extra_args],
            cwd=repo,
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        if not lines:
            return {
                "ok": False,
                "returncode": proc.returncode,
                "stderr_tail": proc.stderr[-1500:],
            }
        # a failed run (rc != 0) still emitted its record: return THAT —
        # the per-invariant *_ok fields beat an opaque stderr tail
        record = json.loads(lines[-1])
        if proc.returncode != 0:
            record.setdefault("returncode", proc.returncode)
            record.setdefault("stderr_tail", proc.stderr[-1500:])
        return record
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:1500]}


def _chaos_smoke() -> dict:
    """Crash-recovery chaos smoke (scripts/chaos_run.py --smoke):
    driver killed between helper ack and leader commit, helper
    transport/5xx storm through the circuit breaker, lease reacquired
    within TTL, and the final collection equal to the admitted ground
    truth exactly."""
    return _run_chaos_subprocess(["--smoke", "--json"], timeout=560)


def _watchdog_overhead(iters: int = 200_000) -> dict:
    """Measure — not assume — the disarmed dispatch-watchdog cost: ns
    per supervised call with NO ambient deadline (the production state
    for un-deadlined paths and the constant prefix for deadlined ones:
    one contextvar read + a None check) against an empty-loop baseline,
    plus the armed-path cost (worker handoff) for context. The
    acceptance bound is ≤ 1 µs/dispatch disarmed."""
    import time as _time

    from janus_tpu.aggregator.device_watchdog import DispatchWatchdog
    from janus_tpu.core.deadline import deadline_scope

    wd = DispatchWatchdog()
    fn = lambda: None  # noqa: E731

    def measure(call) -> float:
        t0 = _time.perf_counter()
        for _ in range(iters):
            call()
        return (_time.perf_counter() - t0) / iters * 1e9

    baseline_ns = measure(fn)
    disarmed_ns = measure(lambda: wd.run(fn))
    # armed: real worker handoff per call (amortized by thread reuse)
    armed_iters = 2_000
    with deadline_scope(_time.monotonic() + 3600):
        t0 = _time.perf_counter()
        for _ in range(armed_iters):
            wd.run(fn, deadline=_time.monotonic() + 60)
        armed_ns = (_time.perf_counter() - t0) / armed_iters * 1e9
    return {
        "iters": iters,
        "baseline_ns": round(baseline_ns, 1),
        "disarmed_ns_per_dispatch": round(disarmed_ns, 1),
        "disarmed_overhead_ns": round(disarmed_ns - baseline_ns, 1),
        "armed_ns_per_dispatch": round(armed_ns, 1),
    }


def _profiler_overhead_record() -> dict:
    """Measure — not assume — the continuous profiler's cost (ISSUE 13
    acceptance: ≤ 2% served-throughput regression with the sampler on):
    a serving-shaped workload (spans around numpy field work, the span
    hot path the sampler sees in production) timed in INTERLEAVED
    blocks with the sampler running at the production 19 Hz vs off
    (median per-pair ratio, GC paused — the codec-bench lesson), plus
    the sampler's own self-measured overhead ratio and a collapsed-
    format well-formedness check under a hostile thread name."""
    import threading as _threading

    import numpy as np

    from janus_tpu import profiler as _prof
    from janus_tpu.trace import span

    rng = np.random.default_rng(0xF0)
    data = rng.integers(0, 2**32 - 1, size=1 << 20).astype(np.uint64)

    def workload():
        # ~100 ms of span-wrapped numpy per block (the serving shape:
        # ms-scale work under spans, which is what the sampler walks) —
        # blocks must be long enough that the per-block sampler
        # start/stop below is sub-permille, or the A/B measures thread
        # lifecycle instead of sampling cost
        acc = data
        for _ in range(24):
            with span("bench.profiler_ab"):
                acc = (acc * np.uint64(6364136223846793005) + np.uint64(1)) % np.uint64(
                    0xFFFFFFFB
                )
        return acc

    cfg = _prof.ProfilerConfig(hz=19.0, window_secs=60.0)

    def sampled():
        p = _prof.SamplingProfiler(cfg)
        p.start()
        try:
            workload()
        finally:
            p.stop()

    # interleaved pairs with ALTERNATING order (GC paused): the signal
    # (~0.3% at 19 Hz) is far below scheduler/cache noise on a shared
    # CI host, and a fixed measurement order leaves a systematic warm/
    # cold bias on one side — alternating cancels it, the median does
    # the rest
    import gc
    import statistics
    import time as _time

    def timed(fn) -> float:
        t0 = _time.perf_counter()
        fn()
        return _time.perf_counter() - t0

    on_ts, off_ts, ratios = [], [], []
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        timed(sampled), timed(workload)  # warm first-touch pages
        for i in range(16):
            if i % 2 == 0:
                s = timed(sampled)
                f = timed(workload)
            else:
                f = timed(workload)
                s = timed(sampled)
            on_ts.append(s)
            off_ts.append(f)
            ratios.append(s / f)
    finally:
        if gc_was_enabled:
            gc.enable()
    on_s, off_s, ratio = min(on_ts), min(off_ts), statistics.median(ratios)
    overhead_pct = max(0.0, (ratio - 1.0) * 100.0)

    # self-measured overhead + hostile-name fold: a fast sampler over a
    # thread whose name carries separators/quotes must yield a
    # well-formed collapsed document (shared validator) and 0 overhead
    # reported once stopped... the ratio itself comes from the window
    p = _prof.SamplingProfiler(_prof.ProfilerConfig(hz=97.0, window_secs=30.0))
    stop = _threading.Event()
    hostile = _threading.Thread(
        target=stop.wait, name='evil;role name\n"x" 42', daemon=True
    )
    hostile.start()
    p.start()
    time.sleep(0.4)
    doc = p.profile_json()
    collapsed = p.collapsed()
    p.stop()
    stop.set()
    fold_errors = _prof.validate_collapsed(collapsed)
    return {
        "sampler_hz": cfg.hz,
        "on_block_s": round(on_s, 4),
        "off_block_s": round(off_s, 4),
        "median_pair_ratio": round(ratio, 4),
        # THE acceptance number: sampler-on vs sampler-off throughput
        # regression (gate: <= 2.0)
        "overhead_pct": round(overhead_pct, 3),
        "gate_ok": overhead_pct <= 2.0,
        "self_measured_overhead_ratio": doc["overhead_ratio"],
        "samples": doc["samples"],
        "roles_seen": sorted(doc["roles"]),
        "collapsed_well_formed": not fold_errors,
        "collapsed_errors": fold_errors[:3],
    }


def _device_hang_smoke() -> dict:
    """Deadline-aware device-path smoke (scripts/chaos_run.py
    --scenario device_hang --smoke): the real driver binary's first
    dispatch wedges forever; the watchdog abandons it inside the lease
    budget, the job steps back (reason=device_hang), the engine runs
    quarantined → canary-probed → restored observed live over
    /metrics + /statusz (incl. the stalled-thread stack dump), interim
    work lands through host fallback, and the final collection equals
    the admitted ground truth exactly."""
    return _run_chaos_subprocess(
        ["--scenario", "device_hang", "--smoke", "--json"], timeout=300
    )


def _resident_chaos_smoke() -> dict:
    """Resident-state flush-contract smoke (scripts/chaos_run.py
    --scenario resident --smoke): the real driver binary with resident
    accumulators on — LRU eviction, mid-stream quarantine sweep, and
    SIGTERM drain each flush resident state through the write-tx path,
    no flush reports outcome=lost, and both tasks' collections equal
    their admitted ground truths exactly."""
    return _run_chaos_subprocess(
        ["--scenario", "resident", "--smoke", "--json"], timeout=300
    )


def _resident_accumulate_record(inst=None, n: int = 256, k: int = 16, jobs: int = 4) -> dict:
    """Resident vs re-stage A/B on the SAME dataset (ISSUE 12): `jobs`
    job steps of `n` out-share rows spread over `k` batch buckets run
    through BOTH accumulate legs on one engine — the classic per-bucket
    path (one n-bool mask upload + one aggregate fetch per bucket per
    job) and the resident path (one [n] int32 upload per job, one fetch
    for the whole run at take time). Reports host<->device bytes per
    report on the accumulate leg from the real janus_engine_hd_bytes
    accounting, rows per dispatch from the real dispatch counter, and
    asserts the aggregate shares BIT-IDENTICAL (field elements mod p).
    The >=2x bytes/report acceptance gate reads this record."""
    import numpy as np

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.messages import Duration, Interval, Time
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    inst = inst or VdafInstance.count()
    eng = EngineCache(inst, bytes(range(16)))
    p = eng.p3.jf.MODULUS
    iv = Interval(Time(0), Duration(3600))
    rng = np.random.default_rng(0xAB12)

    def hd_totals() -> tuple[float, float]:
        return (
            _m.engine_hd_bytes_total.get(direction="h2d"),
            _m.engine_hd_bytes_total.get(direction="d2h"),
        )

    total_rows = n * jobs
    classic_totals: dict[int, list[int]] = {}
    classic_h2d = classic_d2h = 0.0
    resident_h2d = resident_d2h = 0.0
    classic_dispatches = resident_dispatches = 0
    out_shares = []
    lane_buckets = []
    for j in range(jobs):
        meas = random_measurements(inst, n, rng)
        args, _ = make_report_batch(inst, meas, seed=0xC0 + j)
        nonce, public, mv, proof, blind0, _, _ = args
        out0, _, _, _ = eng.leader_init(nonce, public, mv, proof, blind0)
        out_shares.append(out0)
        lane_buckets.append(rng.integers(0, k, size=n).astype(np.int32))

    # --- A: classic re-stage leg (the pre-resident shape) -------------
    d0 = _m.engine_dispatches_total.get(op="aggregate")
    h0, f0 = hd_totals()
    for out0, lane_bucket in zip(out_shares, lane_buckets):
        for j in range(k):
            share = eng.aggregate(out0, lane_bucket == j)
            tot = classic_totals.setdefault(j, [0] * len(share))
            for i, x in enumerate(share):
                tot[i] = (tot[i] + x) % p
    h1, f1 = hd_totals()
    classic_h2d, classic_d2h = h1 - h0, f1 - f0
    classic_dispatches = int(_m.engine_dispatches_total.get(op="aggregate") - d0)

    # --- B: resident leg (same rows, same buckets) --------------------
    d0 = _m.engine_dispatches_total.get(op="aggregate")
    h0, f0 = hd_totals()
    for out0, lane_bucket in zip(out_shares, lane_buckets):
        pend = eng.aggregate_pending(out0, lane_bucket, k)
        entries = [
            ((b"bench-task", b"", b"bucket-%d" % j), j, int((lane_bucket == j).sum()), iv)
            for j in range(k)
        ]
        evicted = eng.resident_merge(entries, pend)
        assert evicted == [], "bench run must not hit the byte cap"
    recs = {r["key"][2]: r["share"] for r in eng.resident_take()}
    h1, f1 = hd_totals()
    resident_h2d, resident_d2h = h1 - h0, f1 - f0
    resident_dispatches = int(_m.engine_dispatches_total.get(op="aggregate") - d0)

    identical = all(
        recs.get(b"bucket-%d" % j) == classic_totals[j] for j in range(k)
    )
    classic_bpr = (classic_h2d + classic_d2h) / total_rows
    resident_bpr = (resident_h2d + resident_d2h) / total_rows
    return {
        "n_per_job": n,
        "jobs": jobs,
        "buckets": k,
        "total_rows": total_rows,
        "classic": {
            "h2d_bytes_per_report": round(classic_h2d / total_rows, 2),
            "d2h_bytes_per_report": round(classic_d2h / total_rows, 2),
            "hd_bytes_per_report": round(classic_bpr, 2),
            "dispatches": classic_dispatches,
            "rows_per_dispatch": round(total_rows / max(1, classic_dispatches), 1),
        },
        "resident": {
            "h2d_bytes_per_report": round(resident_h2d / total_rows, 2),
            "d2h_bytes_per_report": round(resident_d2h / total_rows, 2),
            "hd_bytes_per_report": round(resident_bpr, 2),
            "dispatches": resident_dispatches,
            "rows_per_dispatch": round(total_rows / max(1, resident_dispatches), 1),
        },
        # THE acceptance number: host<->device bytes/report on the
        # accumulate leg, classic / resident (gate: >= 2.0)
        "hd_bytes_per_report_ratio": round(classic_bpr / max(1e-9, resident_bpr), 2),
        "aggregates_identical": identical,
    }


_MESH_SMOKE_MARK = "JANUS_MESH_SMOKE:"

_MESH_SMOKE_CHILD = r'''
import json, time
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
from janus_tpu.aggregator import engine_cache as ec
from janus_tpu.aggregator.engine_cache import EngineCache, mesh_status
from janus_tpu.messages import Duration, Interval, Time
from janus_tpu.vdaf.registry import VdafInstance
from janus_tpu.vdaf.testing import make_report_batch, random_measurements

inst = VdafInstance.sum_vec(length=4, bits=2)
n = 64
rng = np.random.default_rng(0xE5)
args, _ = make_report_batch(inst, random_measurements(inst, n, rng), seed=0xE5)
nonce, parts, meas, proof, blind0, hseed, blind1 = args
eng = EngineCache(inst, bytes(range(16)))
ok = np.ones(n, dtype=bool); ok[::9] = False

def round_once():
    out0, _s, ver0, part0 = eng.leader_init(nonce, parts, meas, proof, blind0)
    part0_l = part0 if part0 is not None else np.zeros((n, 2), dtype=np.uint64)
    out1, _m, _p = eng.helper_init(nonce, parts, hseed, blind1, ver0, part0_l, ok)
    return out0, eng.aggregate(out0, ok), eng.aggregate(out1, ok)

round_once()  # compile round, untimed
t0 = time.monotonic()
out0, agg0, agg1 = round_once()
dt = time.monotonic() - t0
deltas = eng.aggregate_pending(out0, (np.arange(n) % 2).astype(np.int32), 2)
iv = Interval(Time(0), Duration(3600))
eng.resident_merge([(("s", 0), 0, n // 2, iv), (("s", 1), 1, n // 2, iv)], deltas)
res = sorted((str(r["key"]), [str(x) for x in r["share"]]) for r in eng.resident_take())
q = mesh_status()["queue"]
print("JANUS_MESH_SMOKE:" + json.dumps({
    "devices": len(jax.devices()), "dp": eng.dp, "sp": eng.sp,
    "agg0": [str(x) for x in agg0], "agg1": [str(x) for x in agg1],
    "resident": res, "rps": round(n / dt, 2) if dt > 0 else 0.0,
    "queue_submitted": q["submitted"], "queue_errors": q["errors"],
    "lane_alive": q["lane_alive"],
    "dispatch_lock_removed": not hasattr(ec, "_MESH_DISPATCH_LOCK"),
}), flush=True)
'''


def _mesh_serving_smoke() -> dict:
    """Mesh serving smoke (ISSUE 16): ONE subprocess with 4 forced
    virtual CPU devices drives the SERVING EngineCache path — leader +
    helper init, masked aggregate with rejected lanes, sharded
    resident accumulate + flush — over a (dp, sp) mesh behind the
    single-controller dispatch queue; the parent recomputes the SAME
    batch on its single-device engine and asserts every aggregate and
    resident share BIT-IDENTICAL. Gates: bit_identical, mesh active
    (dp*sp > 1), queue submitted > 0 with zero errors, the old
    process-global dispatch lock gone, rps > 0."""
    import subprocess

    import numpy as np

    from janus_tpu.aggregator.engine_cache import EngineCache
    from janus_tpu.messages import Duration, Interval, Time
    from janus_tpu.vdaf.registry import VdafInstance
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    rec: dict = {"ok": False}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count=4".strip()
    env.pop("JANUS_MESH_DP", None)
    env.pop("JANUS_MESH_SP", None)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp_cache")
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _MESH_SMOKE_CHILD],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=420,
        )
    except subprocess.TimeoutExpired:
        rec["error"] = "mesh smoke child timeout"
        return rec
    rec["rc"] = proc.returncode
    child = None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith(_MESH_SMOKE_MARK):
            child = json.loads(line[len(_MESH_SMOKE_MARK):])
            break
    if child is None:
        rec["error"] = "no mesh smoke record in child stdout"
        rec["stderr_tail"] = proc.stderr[-1500:]
        return rec
    rec.update(child)

    # single-device reference through the SAME serving entry points
    inst = VdafInstance.sum_vec(length=4, bits=2)
    n = 64
    rng = np.random.default_rng(0xE5)
    args, _ = make_report_batch(inst, random_measurements(inst, n, rng), seed=0xE5)
    nonce, parts, meas, proof, blind0, hseed, blind1 = args
    ref = EngineCache(inst, bytes(range(16)))
    ok = np.ones(n, dtype=bool)
    ok[::9] = False
    out0, _s, ver0, part0 = ref.leader_init(nonce, parts, meas, proof, blind0)
    part0_l = part0 if part0 is not None else np.zeros((n, 2), dtype=np.uint64)
    out1, _m, _p = ref.helper_init(nonce, parts, hseed, blind1, ver0, part0_l, ok)
    agg0 = [str(x) for x in ref.aggregate(out0, ok)]
    agg1 = [str(x) for x in ref.aggregate(out1, ok)]
    deltas = ref.aggregate_pending(out0, (np.arange(n) % 2).astype(np.int32), 2)
    iv = Interval(Time(0), Duration(3600))
    ref.resident_merge([(("s", 0), 0, n // 2, iv), (("s", 1), 1, n // 2, iv)], deltas)
    res = sorted(
        (str(r["key"]), [str(x) for x in r["share"]]) for r in ref.resident_take()
    )
    # the child's record crossed JSON, so its resident tuples are lists
    rec["bit_identical"] = (
        rec.get("agg0") == agg0
        and rec.get("agg1") == agg1
        and rec.get("resident") == [list(t) for t in res]
    )
    rec["ok"] = bool(
        rec["bit_identical"]
        and rec.get("rc") == 0
        and rec.get("dp", 1) * rec.get("sp", 1) > 1
        and rec.get("queue_submitted", 0) > 0
        and rec.get("queue_errors", 1) == 0
        and rec.get("dispatch_lock_removed")
        and rec.get("rps", 0) > 0
    )
    return rec


def _cold_start_record(full: bool = False) -> dict:
    """Cold-start A/B (scripts/chaos_run.py --scenario cold_start):
    interleaved cold-cache vs warm-cache boots of the REAL driver
    binary, restart-to-first-dispatch measured via /debug/boot (phase
    sums proven exact in the boot-timeline tests). Both boots replay
    the same shape manifest through the AOT prewarm before /readyz
    flips ready; the warm boot loads serialized executables (no
    re-trace) + the persistent XLA cache. Gates: warm under 10 s, warm
    >= 1.5x cold in the tier-1 smoke (>= 3x in the full record), AOT
    saves observed cold / loads observed warm."""
    args = ["--scenario", "cold_start", "--json"]
    if not full:
        args.append("--smoke")
    return _run_chaos_subprocess(args, timeout=900 if full else 420)


def _fleet_scaling_record(full: bool = False) -> dict:
    """Fleet scale-out record (scripts/chaos_run.py --scenario fleet):
    N REAL driver replicas — own fleet identities and shard slices —
    over one leader store under RTT-bound load. Carries the served-rps
    scaling curve (1/2/4 replicas full, 1/2 smoke), the measured
    claim-round-trips-per-job comparison vs the old per-row loop, and
    the kill/drain/restart chaos gates (zero lease conflicts, steal
    drain, exact collection)."""
    args = ["--scenario", "fleet", "--json"]
    if not full:
        args.append("--smoke")
    return _run_chaos_subprocess(args, timeout=900 if full else 480)


def _fleet_smoke() -> dict:
    """In-process fleet smoke (ISSUE 15): TWO driver replicas — each
    with its own fleet identity and shard slice — over ONE datastore.
    Replica A claims its shard's jobs on a 2 s lease and DIES holding
    them (never steps, never releases: the SIGKILL analog), replica B
    finishes its own shard immediately and STEALS A's jobs once their
    leases expire past the steal delay. Gates: every job finishes, the
    collection equals the admitted ground truth exactly, the
    lease-conflict counter stays at zero (nothing double-stepped), B's
    claims were batched (jobs per claim tx > 1), and the dead
    replica's shard drained through the steal fallback."""
    import dataclasses
    import secrets as _secrets
    import tempfile
    import threading

    from janus_tpu import metrics as _m
    from janus_tpu.aggregator import Aggregator, Config
    from janus_tpu.aggregator.aggregation_job_creator import (
        AggregationJobCreator,
        AggregationJobCreatorConfig,
    )
    from janus_tpu.aggregator.aggregation_job_driver import AggregationJobDriver
    from janus_tpu.aggregator.collection_job_driver import CollectionJobDriver
    from janus_tpu.aggregator.http_handlers import DapHttpApp, DapServer
    from janus_tpu.aggregator.job_driver import JobDriver, JobDriverConfig
    from janus_tpu.binary_utils import warmup_engines
    from janus_tpu.client import Client, ClientParameters
    from janus_tpu.collector import Collector, CollectorParameters
    from janus_tpu.config import FleetConfig
    from janus_tpu.core.auth import AuthenticationToken
    from janus_tpu.core.hpke import generate_hpke_config_and_private_key
    from janus_tpu.core.http_client import HttpClient
    from janus_tpu.core.time_util import RealClock
    from janus_tpu.datastore.store import Crypter, Datastore, job_shard_key
    from janus_tpu.messages import Duration, Interval, Query, Role, Time
    from janus_tpu.task import QueryTypeConfig, TaskBuilder
    from janus_tpu.vdaf.registry import VdafInstance

    rec: dict = {}
    tmp = tempfile.mkdtemp(prefix="janus-bench-fleet-")
    key = _secrets.token_bytes(16)
    clock = RealClock()
    leader_ds = Datastore(os.path.join(tmp, "leader.sqlite"), Crypter([key]), clock)
    helper_ds = Datastore(os.path.join(tmp, "helper.sqlite"), Crypter([key]), clock)
    leader_srv = helper_srv = None
    job_size = 2
    try:
        helper_srv = DapServer(DapHttpApp(Aggregator(helper_ds, clock, Config()))).start()
        leader_srv = DapServer(
            DapHttpApp(Aggregator(leader_ds, clock, Config(collection_retry_after_s=1)))
        ).start()
        vdaf = VdafInstance.count()
        collector_kp = generate_hpke_config_and_private_key(config_id=206)
        leader_task = (
            TaskBuilder(QueryTypeConfig.time_interval(), vdaf, Role.LEADER)
            .with_(
                leader_aggregator_endpoint=leader_srv.url,
                helper_aggregator_endpoint=helper_srv.url,
                collector_hpke_config=collector_kp.config,
                aggregator_auth_token=AuthenticationToken.random_bearer(),
                collector_auth_token=AuthenticationToken.random_bearer(),
                min_batch_size=1,
            )
            .build()
        )
        helper_task = dataclasses.replace(
            leader_task,
            role=Role.HELPER,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=6),),
        )
        leader_ds.run_tx(lambda tx: tx.put_task(leader_task), "provision")
        helper_ds.run_tx(lambda tx: tx.put_task(helper_task), "provision")
        warmup_engines(leader_ds, batch=job_size)

        http = HttpClient()
        client = Client.with_fetched_configs(
            ClientParameters(
                leader_task.task_id,
                leader_srv.url,
                helper_srv.url,
                leader_task.time_precision,
            ),
            vdaf,
            http,
            clock=clock,
        )
        creator = AggregationJobCreator(
            leader_ds,
            AggregationJobCreatorConfig(
                min_aggregation_job_size=1, max_aggregation_job_size=job_size
            ),
        )
        measurements = []

        def upload(n):
            wave = [(i % 3 != 0) * 1 for i in range(n)]
            for m in wave:
                client.upload(m)
            measurements.extend(wave)
            creator.run_once()

        def shard_census():
            jobs = leader_ds.run_tx(
                lambda tx: tx.get_aggregation_jobs_for_task(leader_task.task_id),
                "fleet_smoke_census",
            )
            by_shard = {0: 0, 1: 0}
            for j in jobs:
                by_shard[
                    job_shard_key(leader_task.task_id.data, j.job_id.data) % 2
                ] += 1
            return len(jobs), by_shard

        upload(16)
        # both shards must be populated for the steal proof to mean
        # anything; random job ids make an empty shard a ~0.8% event —
        # top up deterministically instead of flaking
        for _ in range(6):
            n_jobs, by_shard = shard_census()
            if by_shard[0] and by_shard[1]:
                break
            upload(job_size)
        rec["jobs"] = n_jobs
        rec["jobs_by_shard"] = by_shard
        rec["both_shards_populated"] = bool(by_shard[0] and by_shard[1])

        fleet_a = FleetConfig(
            replica_id="bench-fleet-a", shard_count=2, shard_index=0, steal_after_secs=1
        )
        fleet_b = FleetConfig(
            replica_id="bench-fleet-b", shard_count=2, shard_index=1, steal_after_secs=1
        )
        conflicts0 = _m.lease_conflicts_total.total()
        steals0 = _m.lease_steals_total.total()
        tx0 = _m.lease_acquire_tx_total.get(kind="aggregation", outcome="claimed")
        jobs0 = _m.lease_acquired_jobs_total.get(kind="aggregation")

        # replica A: claim on a 2 s lease, then die holding the leases
        dead = AggregationJobDriver(leader_ds, http)
        held = dead.acquirer(2, fleet=fleet_a)(16)
        rec["held_by_dead_replica"] = len(held)
        del held  # nothing ever steps or releases these — SIGKILL analog

        # replica B: steps its shard now, steals A's after expiry+delay
        live = AggregationJobDriver(leader_ds, http)
        jd = JobDriver(
            JobDriverConfig(job_discovery_interval_s=0.05, max_concurrent_job_workers=4),
            live.acquirer(60, fleet=fleet_b),
            live.stepper,
        )

        def finished():
            counts = leader_ds.run_tx(
                lambda tx: tx.count_jobs_by_state(), "fleet_smoke_monitor"
            )
            return sum(
                n
                for (typ, state), n in counts.items()
                if typ == "aggregation" and state == "finished"
            )

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and finished() < rec["jobs"]:
            jd.run_once()
            time.sleep(0.05)
        rec["jobs_finished"] = finished()
        rec["survivor_finished_all"] = rec["jobs_finished"] >= rec["jobs"]
        rec["lease_conflicts_delta"] = _m.lease_conflicts_total.total() - conflicts0
        rec["zero_conflicts"] = rec["lease_conflicts_delta"] == 0
        rec["steals_delta"] = _m.lease_steals_total.total() - steals0
        rec["dead_shard_stolen"] = rec["steals_delta"] >= 1
        claim_txs = _m.lease_acquire_tx_total.get(
            kind="aggregation", outcome="claimed"
        ) - tx0
        claimed = _m.lease_acquired_jobs_total.get(kind="aggregation") - jobs0
        rec["claim_txs"] = claim_txs
        rec["jobs_claimed"] = claimed
        rec["jobs_per_claim_tx"] = round(claimed / max(1.0, claim_txs), 2)
        rec["batched_claims"] = claim_txs > 0 and rec["jobs_per_claim_tx"] > 1.0

        # collect and compare against ground truth exactly
        cdrv = CollectionJobDriver(leader_ds, HttpClient())
        stop_collect = threading.Event()

        def collect_loop():
            cjd = JobDriver(
                JobDriverConfig(job_discovery_interval_s=0.2),
                cdrv.acquirer(60),
                cdrv.stepper,
            )
            while not stop_collect.is_set():
                cjd.run_once()
                stop_collect.wait(0.2)

        ct = threading.Thread(target=collect_loop, daemon=True)
        ct.start()
        try:
            collector = Collector(
                CollectorParameters(
                    leader_task.task_id,
                    leader_srv.url,
                    leader_task.collector_auth_token,
                    collector_kp,
                ),
                vdaf,
                HttpClient(),
            )
            tp = leader_task.time_precision
            start = clock.now().to_batch_interval_start(tp)
            query = Query.time_interval(
                Interval(Time(start.seconds - tp.seconds), Duration(3 * tp.seconds))
            )
            collected = collector.collect(query, timeout_s=90.0)
            rec["admitted"] = len(measurements)
            rec["collected_count"] = collected.report_count
            rec["collected_sum"] = collected.aggregate_result
            rec["exactly_once"] = (
                collected.report_count == len(measurements)
                and collected.aggregate_result == sum(measurements)
            )
        finally:
            stop_collect.set()
            ct.join(timeout=10)
        return rec
    finally:
        for srv in (leader_srv, helper_srv):
            if srv is not None:
                srv.stop()
        leader_ds.close()
        helper_ds.close()


def _peer_outage_smoke() -> dict:
    """Peer-outage survival smoke (scripts/chaos_run.py --scenario
    peer_outage --smoke): the real aggregation + collection driver
    binaries reach the helper only through a netsim fault proxy; a
    blackhole past the breaker-open threshold keeps uploads at 201
    while BOTH binaries park (claim transactions frozen,
    janus_peer_parked=1, zero lease conflicts), a cheap half-open
    probe resumes them when the wire heals, slow-drip and mid-body
    truncation lanes recover without wedging a worker, and the
    collections equal the admitted ground truth exactly."""
    return _run_chaos_subprocess(
        ["--scenario", "peer_outage", "--smoke", "--json"], timeout=480
    )


def _db_outage_smoke() -> dict:
    """Datastore-outage survival smoke (scripts/chaos_run.py
    --scenario db_outage --smoke): uploads keep acking 201 through a
    full datastore outage (durable spill journal, fsync-on-ack),
    /readyz flips 503 -> 200 across recovery, the journal drains to
    empty, and the final collection equals every 201-acked report
    exactly once. Healthy-path proof rides along: the armed-but-idle
    journal performed zero fsyncs."""
    return _run_chaos_subprocess(
        ["--scenario", "db_outage", "--smoke", "--json"], timeout=300
    )


def _soak_smoke() -> dict:
    """Endurance-soak smoke (scripts/chaos_run.py --scenario soak
    --smoke): sustained open-loop load with per-epoch task churn and GC
    really deleting expired rows, every epoch collected EXACTLY while
    churn continues, judged by the flight recorder — zero-slope
    verdicts on rss/datastore-rows from the clean driver with recorder
    self-overhead <= 1%, and the injected synthetic leak on the second
    driver flipping janus_flight_leak_active and firing the
    resource_trend SLO alert through the window_scale-shrunk ladder."""
    return _run_chaos_subprocess(
        ["--scenario", "soak", "--smoke", "--json"], timeout=560
    )


def _flight_rider() -> dict:
    """ISSUE 18: the measured run's flight-recorder view — top trend
    slopes, leak verdicts, and the ring's on-disk bytes/hour — from the
    recorder sampling THIS process since bench start."""
    from janus_tpu import flight_recorder as _fr

    fr = _fr.get_flight_recorder()
    if fr is None:
        return {"enabled": False}
    analysis = fr.analyze()
    st = fr.status()
    series = analysis.get("series", {})
    top = sorted(
        (
            (n, d)
            for n, d in series.items()
            if isinstance(d.get("slope_per_s"), (int, float))
        ),
        key=lambda kv: -abs(kv[1]["slope_per_s"]),
    )[:5]
    covered = max(
        (d.get("covered_s") or 0.0 for d in series.values()), default=0.0
    )
    ring = st.get("ring") or {}
    return {
        "enabled": True,
        "snapshots": st.get("snapshots"),
        "overhead_ratio": st.get("overhead_ratio"),
        "top_slopes": [
            {
                "series": n,
                "slope_per_s": d["slope_per_s"],
                "verdict": d.get("verdict"),
            }
            for n, d in top
        ],
        "leak_verdicts": {n: d.get("verdict") for n, d in series.items()},
        "leaking": analysis.get("leaking", []),
        "ring_bytes": ring.get("bytes"),
        "ring_bytes_per_hour": (
            round(ring.get("bytes", 0) * 3600.0 / covered, 1) if covered else None
        ),
    }


# Planning default when the backend reports no memory budget (the axon
# tunnel; CPU): the v5e HBM size the BASELINE.md measurements ran on.
V5E_HBM_BYTES = int(15.75 * (1 << 30))


def _feasibility_record(inst):
    """The HBM model's view of a config: (describe dict, raw device
    budget, stream plan). Shared by --dry-run and the measured run's
    JSON rider so the two can never report different feasibility
    numbers for the same config."""
    from janus_tpu.vdaf import engine
    from janus_tpu.vdaf.feasibility import describe, device_memory_budget
    from janus_tpu.vdaf.registry import circuit_for

    circ = circuit_for(inst)
    plan = engine.stream_plan(engine.batched_circuit(circ))
    budget = device_memory_budget()
    desc = describe(
        circ,
        tile_elems=plan.group if plan is not None else None,
        draft=inst.xof_mode != "fast",
        budget_bytes=budget if budget is not None else V5E_HBM_BYTES,
    )
    return desc, budget, plan


def run_dry(args, ap) -> None:
    """--dry-run: no accelerator required. Prints the HBM feasibility
    model's view of the config (modeled bytes/row, largest safe bucket,
    stream-plan tile geometry), smoke-tests the EngineCache
    bucketing/OOM-fallback path on a toy circuit, smoke-tests the
    admission-controlled ingest pipeline's 429-shed path over loopback
    HTTP, measures the span() tracing overhead, drives the full
    observability surface (live /metrics scrape validation, /statusz,
    profile capture + 409 guard, scrape_check), measures the disarmed
    failpoint hot-path cost, and runs the crash-recovery chaos smoke
    (driver SIGKILL mid-step + helper storms -> exactly-once
    collection; scripts/chaos_run.py), as one JSON line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    inst = _make_inst(args, ap)
    desc, budget, plan = _feasibility_record(inst)
    # order matters: the OOM smoke's real engine dispatches feed
    # janus_engine_dispatch_seconds through the span->metric bridge,
    # which the observability smoke then asserts non-zero over HTTP
    oom_smoke = _oom_fallback_smoke()
    ingest_smoke = _ingest_shed_smoke()
    print(
        json.dumps(
            {
                "metric": "dry_run",
                "config": inst.to_dict(),
                "stream_plan": (
                    {
                        "tile_elems": plan.group,
                        "gcalls": plan.gcalls,
                        "n_steps": plan.n_steps,
                    }
                    if plan is not None
                    else None
                ),
                "feasibility": desc,
                "device_budget_bytes": budget,
                "modeled_budget_bytes": budget if budget is not None else V5E_HBM_BYTES,
                "oom_fallback_smoke": oom_smoke,
                "ingest_smoke": ingest_smoke,
                "tracing_overhead": _tracing_overhead(),
                "observability_smoke": _observability_smoke(),
                "failpoint_overhead": _failpoint_overhead(),
                "watchdog_overhead": _watchdog_overhead(),
                # ISSUE 13: the continuous profiler's measured cost
                # (sampler on/off A/B, <= 2% gate) + hostile-name fold
                "profiler_overhead": _profiler_overhead_record(),
                "chaos_smoke": _chaos_smoke(),
                "db_outage_smoke": _db_outage_smoke(),
                # ISSUE 19: the other aggregator behind a hostile wire
                # (netsim fault proxy) — peer-outage parking, half-open
                # probe recovery, slow-drip/truncation survival
                "peer_outage_smoke": _peer_outage_smoke(),
                "device_hang_smoke": _device_hang_smoke(),
                # ISSUE 14: cold-cache vs warm-cache real-binary boots —
                # the warm number (restart-to-first-dispatch) is gated
                # under 10 s and must beat cold by the smoke ratio
                "cold_start": _cold_start_record(),
                # ISSUE 12: resident vs re-stage accumulate A/B
                # (bit-identical shares asserted; the >=2x bytes/report
                # gate reads hd_bytes_per_report_ratio) + the live
                # flush-contract proof against the real driver binary
                "resident_accumulate": _resident_accumulate_record(inst),
                "resident_smoke": _resident_chaos_smoke(),
                # ISSUE 9: columnar wire codec vs the per-report loop
                # (bit-identical bytes asserted) + the stage-pipeline
                # overlap proof against the REAL driver binary
                "step_pipeline": {"codec": _codec_speed_record(inst)},
                "pipeline_smoke": _pipeline_smoke(),
                # ISSUE 11: batched ingest crypto/decode — server-side
                # speed vs the per-report oracle (bit-identical stored
                # reports asserted), a real loopback burst through the
                # batched path, and the open-loop upload-overload
                # p50/p99 + shed split
                "upload_batch_speed": _upload_batch_speed_record(inst, window=256),
                "ingest_batch_smoke": _ingest_batch_smoke(),
                "open_loop_upload": _open_loop_upload_record(),
                # ISSUE 15: two in-process fleet replicas over one
                # store — one dies holding its batched claims, the
                # survivor steals the dead shard after the delay and
                # the collection stays exact (the full fleet_scaling
                # record with REAL replica binaries rides measured
                # BENCH runs and chaos_run.py --scenario fleet)
                "fleet_smoke": _fleet_smoke(),
                # ISSUE 16: mesh serving smoke — 4 forced virtual
                # devices drive the serving EngineCache path through
                # the single-controller dispatch queue; aggregates and
                # resident shares bit-identical to the single-device
                # reference computed in this process
                "mesh_serving_smoke": _mesh_serving_smoke(),
                # ISSUE 17: block-sparse scatter-merge — sparse vs the
                # dense expanded oracle, bit-identical on both the
                # classic and resident paths, scatter ledger rows proven
                "sparse_scatter": _sparse_scatter_smoke(),
                # ISSUE 18: endurance soak under churn + GC, judged by
                # flight-recorder trend verdicts (zero-slope clean
                # driver, injected leak fires the trend alert, recorder
                # self-overhead <= 1%)
                "soak_smoke": _soak_smoke(),
                # ISSUE 20: report-flow conservation ledger — balanced
                # books through the real admission path, then an
                # injected silent loss (ledger.drop_report) detected as
                # a +1 ingest imbalance on the next evaluation, breach
                # + conservation SLO firing on the same tick
                "ledger_smoke": _ledger_smoke(),
            }
        )
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    # Default is the north-star config (BASELINE.md): SumVec(len=1000,
    # bits=16) two-party prepare+accumulate. Chip-proven since the
    # counter-mode XOF + anti-recompute-barrier rework: compiles in
    # ~173s through the tunnel and sustains ~585 report-shares/s/chip.
    ap.add_argument(
        "--config",
        default="sumvec",
        choices=["count", "sum", "sumvec", "histogram", "fixedpoint", "sparse", "poplar1"],
    )
    ap.add_argument("--batch", type=int, default=0, help="0 = auto per backend")
    ap.add_argument(
        "--length",
        type=int,
        default=0,
        help="override the vector length for sumvec/histogram/fixedpoint "
        "(0 = the BASELINE.md config)",
    )
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument(
        "--xof-mode",
        default="fast",
        choices=["fast", "draft"],
        help="fast = the TPU counter-mode framing (BASELINE.md); draft "
        "= the VDAF-07 spec framing (sequential sponge + rejection "
        "sampling, device engine via vdaf.draft_jax)",
    )
    ap.add_argument(
        "--mode",
        default="device",
        choices=["device", "served"],
        help="device = fused two-party step only; served = also drive "
        "reports through the real HTTP serving path (HPKE + decode + "
        "SQLite + engine) and report both numbers",
    )
    ap.add_argument(
        "--reports", type=int, default=256, help="report count for --mode served"
    )
    ap.add_argument("--host-reports", type=int, default=2, help="reports for the host baseline")
    ap.add_argument(
        "--dry-run",
        action="store_true",
        help="no accelerator: print the HBM feasibility model for the "
        "config (modeled row bytes, largest safe bucket, stream tile) "
        "and smoke-test the EngineCache OOM retry/host-fallback path "
        "on CPU, then exit",
    )
    ap.add_argument(
        "--bringup-deadline-seconds",
        type=float,
        default=600.0,
        help="global wall-clock budget for accelerator bring-up, measured "
        "from the FIRST process (it survives the stall/OOM re-execs via "
        "JANUS_BENCH_T0): once passed, stall recovery stops resting/"
        "retrying the accelerator and re-execs pinned to CPU so the run "
        "always emits a parseable BENCH json (the r5 driver artifact "
        "was rc=124/parsed:null because init rests consumed the whole "
        "window). 0 disables.",
    )
    ap.add_argument(
        "--max-seconds",
        type=float,
        default=1500.0,  # must exceed the worst remote-compile stretch
        # (len=100k mm-query step at batch 64: observed past 900 s
        # through the tunnel's remote AOT compiler; the watchdog's job
        # is wedged-grant detection, and 25 min still catches those)
        help="watchdog: if the accelerator path stalls past this (wedged "
        "tunnel grant), re-exec pinned to CPU so a real measurement is "
        "still produced",
    )
    args = ap.parse_args()

    if args.dry_run:
        if args.config == "poplar1":
            ap.error("--dry-run models Prio3 prepare; poplar1 has no FLP circuit")
        run_dry(args, ap)
        return

    # ISSUE 18: sample this process for the whole measured run so the
    # BENCH json carries the flight rider (top trend slopes, leak
    # verdicts, ring bytes/hour) — never let the recorder kill the run
    try:
        import tempfile as _tempfile

        from janus_tpu import flight_recorder as _fr_mod

        _fr_mod.install_flight_recorder(
            _fr_mod.FlightRecorderConfig(
                interval_s=1.0,
                window_s=1800.0,
                dir=os.path.join(
                    _tempfile.mkdtemp(prefix="janus-bench-flight-"), "ring"
                ),
            )
        )
    except Exception:
        pass

    # bring-up clock: starts in the first process and survives every
    # re-exec (stall retries, OOM halving) via the environment
    bringup_t0 = float(os.environ.setdefault("JANUS_BENCH_T0", str(time.time())))

    def _bringup_deadline_passed() -> bool:
        return (
            args.bringup_deadline_seconds > 0
            and time.time() - bringup_t0 > args.bringup_deadline_seconds
        )

    # Watchdog against a wedged axon tunnel. The tunnel's chip grant can
    # take minutes to release after the previous holder exits, and a
    # process that starts too early blocks forever (registration is
    # one-shot at interpreter start). Strategy: stall -> rest -> re-exec
    # for a fresh registration; after several attempts, pin the CPU
    # backend so a real (if slower) measurement is still produced.
    progress = {"t": time.monotonic(), "done": False}
    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") != "1" and args.max_seconds > 0:
        import threading

        def _fallback():
            # stall = no stage progress for max_seconds (a slow-but-alive
            # accelerator run keeps bumping progress["t"] and is left alone)
            if progress["done"]:
                return
            idle = time.monotonic() - progress["t"]
            if idle < args.max_seconds:
                rearm = threading.Timer(args.max_seconds - idle, _fallback)
                rearm.daemon = True
                rearm.start()
                return
            attempt = int(os.environ.get("JANUS_BENCH_ATTEMPT", "0"))
            if attempt < 3 and not _bringup_deadline_passed():
                print(
                    f"[bench] stalled (attempt {attempt}); resting 150s then retrying axon",
                    file=sys.stderr,
                    flush=True,
                )
                time.sleep(150)
                if progress["done"] or time.monotonic() - progress["t"] < 150:
                    return  # the run came back to life during the rest
                os.environ["JANUS_BENCH_ATTEMPT"] = str(attempt + 1)
            else:
                if _bringup_deadline_passed():
                    print(
                        "[bench] bring-up deadline passed while stalled; no more rests",
                        file=sys.stderr,
                        flush=True,
                    )
                print("[bench] accelerator unusable; re-exec on CPU backend", file=sys.stderr, flush=True)
                os.environ["JANUS_BENCH_CPU_FALLBACK"] = "1"
                os.environ["JAX_PLATFORMS"] = "cpu"
            os.execv(sys.executable, [sys.executable] + sys.argv)

        watchdog = threading.Timer(args.max_seconds, _fallback)
        watchdog.daemon = True
        watchdog.start()
    else:
        watchdog = None

    import jax
    import numpy as np

    _enable_compile_cache()

    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") == "1":
        # sitecustomize may have pinned the axon platform; override in
        # process (env alone is not enough once jax is preimported)
        jax.config.update("jax_platforms", "cpu")

    # The axon tunnel registers the chip at interpreter start and the
    # registration can fail transiently (single-process grant, slow
    # release after a previous holder dies). A failed registration is
    # not recoverable in-process: rest, then re-exec ourselves fresh.
    attempt = int(os.environ.get("JANUS_BENCH_ATTEMPT", "0"))
    try:
        backend = jax.default_backend()
        jax.devices()
    except RuntimeError as e:
        if os.environ.get("JANUS_BENCH_CPU_FALLBACK") == "1":
            raise  # even the CPU backend failed; nothing left to try
        if attempt >= 4 or _bringup_deadline_passed():
            # out of bring-up budget: pin CPU and re-exec so the run
            # still emits a parseable BENCH json instead of rc=124
            print(
                f"backend init failed ({e}); bring-up budget exhausted, "
                "falling back to the CPU backend",
                file=sys.stderr,
                flush=True,
            )
            os.environ["JANUS_BENCH_CPU_FALLBACK"] = "1"
            os.environ["JAX_PLATFORMS"] = "cpu"
        else:
            print(f"backend init failed ({e}); retrying in 90s", file=sys.stderr, flush=True)
            time.sleep(90)
            os.environ["JANUS_BENCH_ATTEMPT"] = str(attempt + 1)
        os.execv(sys.executable, [sys.executable] + sys.argv)
    on_accel = backend not in ("cpu",)

    from janus_tpu.parallel.api import two_party_step
    from janus_tpu.vdaf.registry import VdafInstance, prio3_host
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    if args.config == "poplar1":
        if args.mode != "device" or args.length or args.xof_mode != "fast":
            ap.error(
                "--config poplar1 supports only --mode device with the "
                "fixed Poplar1<16> config (no --length/--xof-mode)"
            )
        run_poplar1(args, backend, progress, watchdog)
        return

    # BASELINE.md measurement configs
    inst = _make_inst(args, ap)
    batch = args.batch or (
        {"count": 8192, "sum": 16384, "sumvec": 2048, "histogram": 1024, "fixedpoint": 1024, "sparse": 1024}[args.config]
        if on_accel
        else {"count": 256, "sum": 128, "sumvec": 16, "histogram": 16, "fixedpoint": 16, "sparse": 16}[args.config]
    )

    rng = np.random.default_rng(0xBE7C)
    verify_key = bytes(range(16))

    def _is_oom(e: Exception) -> bool:
        s = str(e)
        # the axon tunnel's remote AOT compile reports HBM exhaustion as
        # an opaque compile-helper HTTP 500 (details only on its own
        # stderr); treat it as probably-OOM and let the halving loop
        # bottom out at batch 1 if it is something else
        return (
            "RESOURCE_EXHAUSTED" in s
            or "Out of memory" in s
            or "OOM" in s
            or "remote_compile: HTTP 500" in s
        )

    def _is_transient(e: Exception) -> bool:
        # tunnel hiccups that a fresh attempt typically clears — 5xx
        # and torn-connection reads only; deterministic compile errors
        # (4xx, compiler diagnostics) must surface immediately
        s = str(e)
        return (
            "UNAVAILABLE" in s
            or "response body closed" in s
            or ("remote_compile" in s and "HTTP 5" in s)
        )

    def measure_device(inst, batch: int, iters: int, reexec_on_oom: bool = True):
        """Stage + compile + time the two-party step, halving the batch
        on device OOM so long-vector configs always produce a number
        unattended. Returns (device_rps, batch, compile_s)."""
        # stage in prove-sized sub-batches for long vectors (the prove
        # graph peaks at [chunk, arity, n2]; prepare has no such tensor).
        # Sparse configs stage at the COMPACT width, not the logical one.
        eff_len = (
            inst.max_blocks * inst.block_size
            if inst.kind == "sparse_sumvec"
            else getattr(inst, "length", 0)
        )
        shard_chunk = 8 if eff_len * max(inst.bits, 1) > (1 << 18) else 0
        while True:
            try:
                meas = random_measurements(inst, batch, rng)
                t0 = time.time()
                step_args, _ = make_report_batch(inst, meas, seed=1, shard_chunk=shard_chunk)
                progress["t"] = time.monotonic()
                print(
                    f"[bench] backend={backend} batch={batch} shard: {time.time()-t0:.1f}s",
                    file=sys.stderr,
                    flush=True,
                )
                # stage the report columns DEVICE-RESIDENT before
                # timing: the metric is per-chip step throughput
                # (compute + HBM). Through the axon tunnel (~20 MB/s)
                # host-resident args re-transfer per call — at len=100k
                # that is 25.6 MB/report and caps any measurement at
                # <1 r/s, measuring the link, not the chip (deployed
                # PCIe moves the same bytes in ~2.5 ms/report).
                step_args = jax.device_put(step_args)
                jax.block_until_ready(step_args)
                progress["t"] = time.monotonic()
                step = jax.jit(two_party_step(inst, verify_key))
                t0 = time.time()
                out = step(*step_args)
                # int() forces a value fetch = actual remote completion
                # (block_until_ready returns early on the tunnel backend)
                assert int(out[2]) == batch, f"reports rejected: {int(out[2])}/{batch}"
                compile_s = time.time() - t0
                progress["t"] = time.monotonic()
                print(
                    f"[bench] two_party_step compile+first: {compile_s:.1f}s",
                    file=sys.stderr,
                    flush=True,
                )
                break
            except Exception as e:
                # device OOM can surface as JaxRuntimeError or other
                # wrappers depending on which phase hits it; match on
                # the message, not the type
                transient = _is_transient(e) and not _is_oom(e)
                if (not _is_oom(e) and not transient) or batch <= 1 or not reexec_on_oom:
                    raise
                # A hard allocation OOM poisons the tunnel device for
                # the rest of the process (measured: after one OOM at
                # batch 4096, even batch-1 retries ResourceExhausted) —
                # in-process halving cannot recover. Re-exec with the
                # halved batch so the fresh process gets a fresh grant.
                if int(os.environ.get("JANUS_BENCH_OOM_DEPTH", "0")) >= 8:
                    raise
                os.environ["JANUS_BENCH_OOM_DEPTH"] = str(
                    int(os.environ.get("JANUS_BENCH_OOM_DEPTH", "0")) + 1
                )
                next_batch = batch if transient else batch // 2
                argv = [a for a in sys.argv]
                if "--batch" in argv:
                    i = argv.index("--batch")
                    argv[i + 1] = str(next_batch)
                else:
                    argv += ["--batch", str(next_batch)]
                kind = "transient tunnel error" if transient else "device OOM"
                print(
                    f"[bench] {kind} at batch={batch}; re-exec with batch={next_batch}",
                    file=sys.stderr,
                    flush=True,
                )
                progress["t"] = time.monotonic()  # hold the stall watchdog off
                time.sleep(60)  # let the tunnel grant release
                progress["t"] = time.monotonic()
                os.execv(sys.executable, [sys.executable] + argv)

        t0 = time.time()
        for _ in range(iters):
            out = step(*step_args)
            # force a VALUE fetch per iteration: on the tunnel backend
            # block_until_ready returns before remote execution
            # completes (measured: a 0.7s step "finished" in 2ms), so
            # async-pipelined timing without a fetch under-counts
            assert int(out[2]) == batch
            progress["t"] = time.monotonic()
        elapsed = time.time() - t0
        progress["t"] = time.monotonic()
        return batch * iters / elapsed, batch, compile_s

    device_rps, batch, compile_s = measure_device(inst, batch, args.iters)

    # the literal north-star config (BASELINE.json configs[2]:
    # SumVec len=100k) rides along on the default driver run so every
    # BENCH_r{N}.json witnesses it (VERDICT r3 item #2)
    north_star = None
    if args.config == "sumvec" and not args.length and args.mode == "device" and on_accel and args.xof_mode == "fast":
        # (fast mode only: draft-mode len=100k runs on device since r5
        # but at ~1.3-5 r/s with ~50 s steps — measured separately,
        # scripts/measure_draft_sponge.py --full-prepare; BASELINE.md
        # "Draft mode")
        import dataclasses

        ns_inst = dataclasses.replace(inst, length=100_000)
        for attempt in range(3):  # the tunnel flakes transiently
            try:
                # batch 64 is the measured r5 optimum (100.8 r/s; 32
                # gives 83.3 — the dispatch floor is ~2x better
                # amortized at 64 and HBM still fits)
                ns_rps, ns_batch, ns_compile = measure_device(ns_inst, 64, max(2, args.iters // 2), reexec_on_oom=False)
                north_star = {
                    "metric": "prio3_sumvec_len100k_two_party_prepare_accumulate",
                    "value": round(ns_rps, 2),
                    "unit": "report_shares_per_sec_per_chip",
                    "batch": ns_batch,
                    "compile_s": round(ns_compile, 1),
                }
                break
            except Exception as e:  # never lose the main record to the rider
                north_star = {"error": str(e)[:300]}
                progress["t"] = time.monotonic()
                if _is_oom(e):
                    break  # an OOM poisons the tunnel device in-process
                if attempt < 2:
                    time.sleep(30)

    def measure_sparse(sp_batch: int, sp_iters: int) -> dict:
        """The block-sparse north-star (ISSUE 17): two-party prepare at
        the compact width PLUS the gather/scatter-add of every verified
        report's blocks into one dense logical len-1M resident
        accumulator — the full serving device path, timed end to end.
        µs/report comes from the device cost ledger's scatter_merge op;
        the resident HBM figure is the one dense logical row the
        accumulator owns regardless of report count."""
        from janus_tpu.aggregator.engine_cache import EngineCache
        from janus_tpu.profiler import DEVICE_COST
        from janus_tpu.vdaf.registry import circuit_for
        from janus_tpu.vdaf.testing import sparse_compact_batch
        from janus_tpu.vdaf.wire import flat_scatter_indices

        sp_inst = (
            inst
            if inst.kind == "sparse_sumvec"
            else VdafInstance.sparse_sumvec(
                bits=16, length=1_000_000, block_size=64, max_blocks=16
            )
        )
        circ = circuit_for(sp_inst)
        sp_meas = random_measurements(sp_inst, sp_batch, rng)
        t0 = time.time()
        (nonce, public, mv, proof, blind0, seeds, blind1), _ = make_report_batch(
            sp_inst, sp_meas, seed=2
        )
        _, block_idx = sparse_compact_batch(sp_inst, sp_meas)
        flat_idx = flat_scatter_indices(block_idx, circ)
        progress["t"] = time.monotonic()
        print(
            f"[bench] sparse shard: {time.time()-t0:.1f}s",
            file=sys.stderr,
            flush=True,
        )
        eng = EngineCache(sp_inst, verify_key)
        ok = np.ones(sp_batch, dtype=bool)

        def step():
            out0, _, ver0, part0 = eng.leader_init(nonce, public, mv, proof, blind0)
            _, accept, _ = eng.helper_init(
                nonce, public, seeds, blind1, ver0, part0, ok
            )
            assert bool(accept.all()), "sparse bench reports rejected"
            return eng.aggregate_sparse(out0, accept, flat_idx)

        t0 = time.time()
        step()  # compile + first dispatch
        compile_s = time.time() - t0
        progress["t"] = time.monotonic()
        print(
            f"[bench] sparse step compile+first: {compile_s:.1f}s",
            file=sys.stderr,
            flush=True,
        )
        t0 = time.time()
        for _ in range(sp_iters):
            step()
            progress["t"] = time.monotonic()
        rps = sp_batch * sp_iters / (time.time() - t0)
        return {
            "metric": "prio3_sparse_sumvec_len1m_two_party_prepare_scatter",
            "value": round(rps, 2),
            "unit": "report_shares_per_sec_per_chip",
            "batch": sp_batch,
            "iters": sp_iters,
            "compile_s": round(compile_s, 1),
            "logical_length": circ.logical_length,
            "block_size": circ.block_size,
            "max_blocks": circ.max_blocks,
            "resident_hbm_bytes": circ.logical_length * eng.p3.jf.LIMBS * 8,
            "scatter_rows": eng._scatter_rows,
            "block_occupancy": eng._sparse_last_occupancy,
            "us_per_report": DEVICE_COST.us_per_report().get("scatter_merge"),
            "mesh_fallback_reason": eng.mesh_fallback_reason,
        }

    # the block-sparse north-star rides the default driver run (like
    # north_star_len100k) and IS the main measurement for --config sparse
    sparse_northstar = None
    if args.config == "sparse" or (
        args.config == "sumvec"
        and not args.length
        and args.mode == "device"
        and on_accel
        and args.xof_mode == "fast"
    ):
        try:
            sparse_northstar = measure_sparse(
                batch if args.config == "sparse" else (1024 if on_accel else 16),
                args.iters if args.config == "sparse" else max(2, args.iters // 2),
            )
        except Exception as e:  # never lose the main record to the rider
            sparse_northstar = {"error": str(e)[:300]}
            progress["t"] = time.monotonic()

    served = None
    if args.mode == "served":
        served = run_served(inst, args.reports, min(batch, 512), progress)

    # host (CPU oracle) baseline, extrapolated per report. For long
    # vectors the oracle is too slow to run at full length inside the
    # watchdog window; measure at a capped length and scale LINEARLY in
    # the vector length — conservative, since the FLP cost is
    # superlinear (NTT + sqrt-chunked gadget), so linear scaling
    # overstates the host and understates vs_baseline.
    host_len_cap = 2000
    host_inst = inst
    host_scale = 1.0
    if inst.length > host_len_cap and inst.kind in ("sumvec", "histogram", "fixedpoint", "countvec"):
        import dataclasses

        host_inst = dataclasses.replace(inst, length=host_len_cap)
        host_scale = inst.length / host_len_cap
    host = prio3_host(host_inst)
    host_meas = random_measurements(host_inst, args.host_reports, rng)
    t0 = time.time()
    for i in range(args.host_reports):
        mi = host_meas[i]
        if isinstance(mi, list):  # sparse pair-measurement, pass as-is
            m = mi
        else:
            m = mi.tolist() if getattr(mi, "ndim", 0) else int(mi)
        nonce = bytes(16)
        public, (ls, hs) = host.shard(m, nonce)
        st0, ps0 = host.prepare_init(verify_key, 0, nonce, public, ls)
        st1, ps1 = host.prepare_init(verify_key, 1, nonce, public, hs)
        prep = host.prepare_shares_to_prep([ps0, ps1])
        host.prepare_next(st0, prep)
        host.prepare_next(st1, prep)
        progress["t"] = time.monotonic()
    host_s_per_report = (time.time() - t0) * host_scale / args.host_reports
    # the host loop above includes shard(); prepare is ~2/3 of it — keep
    # the conservative (higher) host number by not discounting
    host_rps = 1.0 / host_s_per_report if host_s_per_report > 0 else float("inf")

    progress["done"] = True  # silences any re-armed watchdog timer
    if watchdog is not None:
        watchdog.cancel()
    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") == "1":
        backend = f"{backend} (cpu fallback: accelerator stalled)"

    # achieved bucket + peak HBM per config (ISSUE r6): the feasibility
    # model's view of this circuit plus the device's own high-water
    # mark, so every BENCH_r{N}.json records whether the run was
    # memory-bounded and what bucket the serving engine would pick.
    hbm = {}
    try:
        hbm["feasibility"], _, _ = _feasibility_record(inst)
        stats = jax.local_devices()[0].memory_stats() or {}
        if stats.get("peak_bytes_in_use"):
            hbm["peak_hbm_bytes"] = int(stats["peak_bytes_in_use"])
    except Exception:  # the record must never die to the rider
        pass
    riders = {}
    try:
        # the span() hot path claims to be near-free; measure it in the
        # same record the throughput numbers live in
        riders["tracing_overhead"] = _tracing_overhead()
    except Exception:
        pass
    try:
        # ISSUE 9: measured step_pipeline record — codec speed on this
        # config's circuit, plus the overlap numbers from the served
        # phase when it ran (the dry-run form gets them from
        # pipeline_smoke against the real driver binary)
        riders["step_pipeline"] = {
            "codec": _codec_speed_record(inst),
            **(
                {
                    "overlap_ratio": served["step_pipeline"]["overlap_ratio"],
                    "device_lane_busy_ratio": served["step_pipeline"][
                        "device_lane_busy_ratio"
                    ],
                }
                if served and served.get("step_pipeline")
                else {}
            ),
        }
    except Exception:
        pass
    try:
        # ISSUE 11: batched ingest crypto — measured on this config's
        # circuit — plus the open-loop upload-overload numbers
        riders["ingest_batch"] = {
            "upload_batch_speed": _upload_batch_speed_record(inst, window=256),
            "open_loop_upload": _open_loop_upload_record(),
        }
    except Exception:
        pass
    try:
        # ISSUE 12: resident vs re-stage accumulate A/B on this
        # config's circuit (the >=2x bytes/report acceptance gate)
        riders["resident_accumulate"] = _resident_accumulate_record(inst)
    except Exception:
        pass
    try:
        # ISSUE 14: the warm-vs-cold BENCH record — full form (two
        # vdafs, 2 interleaved pairs, >= 3x gate, warm < 10 s)
        riders["cold_start"] = _cold_start_record(full=True)
    except Exception:
        pass
    try:
        # ISSUE 15: fleet scale-out — served rps at 1/2/4 REAL driver
        # replicas over one store, claim round-trips per job vs the
        # per-row loop, kill/drain/restart chaos gates
        riders["fleet_scaling"] = _fleet_scaling_record(full=True)
    except Exception:
        pass
    try:
        # ISSUE 18: the flight recorder's trend view of this very run
        riders["flight"] = _flight_rider()
    except Exception:
        pass
    if args.mode != "served":
        # the served phase already embeds a scraped snapshot; give the
        # device-only record the registry view so observability data
        # rides every BENCH json
        try:
            riders["metrics_snapshot"] = _metrics_snapshot_rider()
        except Exception:
            pass
    print(
        json.dumps(
            {
                "metric": f"prio3_{args.config}_two_party_prepare_accumulate",
                "value": round(device_rps, 2),
                "unit": "report_shares_per_sec_per_chip",
                "vs_baseline": round(device_rps / host_rps, 2),
                "backend": backend,
                "batch": batch,
                "iters": args.iters,
                "compile_s": round(compile_s, 1),
                "host_oracle_rps": round(host_rps, 3),
                "host_oracle_extrapolated": host_scale != 1.0,
                **({"north_star_len100k": north_star} if north_star else {}),
                **({"sparse_northstar": sparse_northstar} if sparse_northstar else {}),
                **({"served": served} if served else {}),
                **hbm,
                **riders,
                "config": inst.to_dict(),
            }
        )
    )


if __name__ == "__main__":
    main()
