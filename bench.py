"""Benchmark: batched two-party Prio3 prepare+accumulate throughput.

Measures the north-star metric of BASELINE.md: report-shares/sec/chip
for the full two-party prepare + accumulate step (leader init + helper
init + combine/decide + masked aggregate — everything the reference
does per report in aggregation_job_driver.rs:329-402,530-726 and
aggregator.rs:1775-1826), on whatever accelerator JAX exposes.

CPU baseline: the host oracle (janus_tpu.vdaf.reference) timed on a few
reports and extrapolated. The reference's own prio-rs CPU path cannot
run in this image (no Rust toolchain); the host oracle stands in as
the measured-CPU column of BASELINE.md. vs_baseline is
device_throughput / host_throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    # Default is the north-star config (BASELINE.md): SumVec(len=1000,
    # bits=16) two-party prepare+accumulate. Chip-proven since the
    # counter-mode XOF + anti-recompute-barrier rework: compiles in
    # ~173s through the tunnel and sustains ~585 report-shares/s/chip.
    ap.add_argument(
        "--config",
        default="sumvec",
        choices=["count", "sum", "sumvec", "histogram", "fixedpoint"],
    )
    ap.add_argument("--batch", type=int, default=0, help="0 = auto per backend")
    ap.add_argument(
        "--length",
        type=int,
        default=0,
        help="override the vector length for sumvec/histogram/fixedpoint "
        "(0 = the BASELINE.md config)",
    )
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--host-reports", type=int, default=2, help="reports for the host baseline")
    ap.add_argument(
        "--max-seconds",
        type=float,
        default=420.0,
        help="watchdog: if the accelerator path stalls past this (wedged "
        "tunnel grant), re-exec pinned to CPU so a real measurement is "
        "still produced",
    )
    args = ap.parse_args()

    # Watchdog against a wedged axon tunnel. The tunnel's chip grant can
    # take minutes to release after the previous holder exits, and a
    # process that starts too early blocks forever (registration is
    # one-shot at interpreter start). Strategy: stall -> rest -> re-exec
    # for a fresh registration; after several attempts, pin the CPU
    # backend so a real (if slower) measurement is still produced.
    progress = {"t": time.monotonic(), "done": False}
    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") != "1" and args.max_seconds > 0:
        import threading

        def _fallback():
            # stall = no stage progress for max_seconds (a slow-but-alive
            # accelerator run keeps bumping progress["t"] and is left alone)
            if progress["done"]:
                return
            idle = time.monotonic() - progress["t"]
            if idle < args.max_seconds:
                rearm = threading.Timer(args.max_seconds - idle, _fallback)
                rearm.daemon = True
                rearm.start()
                return
            attempt = int(os.environ.get("JANUS_BENCH_ATTEMPT", "0"))
            if attempt < 3:
                print(
                    f"[bench] stalled (attempt {attempt}); resting 150s then retrying axon",
                    file=sys.stderr,
                    flush=True,
                )
                time.sleep(150)
                if progress["done"] or time.monotonic() - progress["t"] < 150:
                    return  # the run came back to life during the rest
                os.environ["JANUS_BENCH_ATTEMPT"] = str(attempt + 1)
            else:
                print("[bench] accelerator unusable; re-exec on CPU backend", file=sys.stderr, flush=True)
                os.environ["JANUS_BENCH_CPU_FALLBACK"] = "1"
                os.environ["JAX_PLATFORMS"] = "cpu"
            os.execv(sys.executable, [sys.executable] + sys.argv)

        watchdog = threading.Timer(args.max_seconds, _fallback)
        watchdog.daemon = True
        watchdog.start()
    else:
        watchdog = None

    import jax
    import numpy as np

    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") == "1":
        # sitecustomize may have pinned the axon platform; override in
        # process (env alone is not enough once jax is preimported)
        jax.config.update("jax_platforms", "cpu")

    # The axon tunnel registers the chip at interpreter start and the
    # registration can fail transiently (single-process grant, slow
    # release after a previous holder dies). A failed registration is
    # not recoverable in-process: rest, then re-exec ourselves fresh.
    attempt = int(os.environ.get("JANUS_BENCH_ATTEMPT", "0"))
    try:
        backend = jax.default_backend()
        jax.devices()
    except RuntimeError as e:
        if attempt >= 4:
            raise
        print(f"backend init failed ({e}); retrying in 90s", file=sys.stderr, flush=True)
        time.sleep(90)
        os.environ["JANUS_BENCH_ATTEMPT"] = str(attempt + 1)
        os.execv(sys.executable, [sys.executable] + sys.argv)
    on_accel = backend not in ("cpu",)

    from janus_tpu.parallel.api import two_party_step
    from janus_tpu.vdaf.registry import VdafInstance, prio3_host
    from janus_tpu.vdaf.testing import make_report_batch, random_measurements

    # BASELINE.md measurement configs
    if args.length and args.config in ("count", "sum"):
        ap.error(f"--length has no meaning for --config {args.config}")
    L = args.length
    inst = {
        "count": VdafInstance.count(),
        "sum": VdafInstance.sum(bits=32),
        "sumvec": VdafInstance.sum_vec(length=L or 1000, bits=16),
        "histogram": VdafInstance.histogram(length=L or 10000),
        "fixedpoint": VdafInstance.fixed_point_vec(length=L or 1000, bits=16),
    }[args.config]
    batch = args.batch or (
        {"count": 8192, "sum": 4096, "sumvec": 1024, "histogram": 512, "fixedpoint": 512}[args.config]
        if on_accel
        else {"count": 256, "sum": 128, "sumvec": 16, "histogram": 16, "fixedpoint": 16}[args.config]
    )

    rng = np.random.default_rng(0xBE7C)
    meas = random_measurements(inst, batch, rng)
    t0 = time.time()
    step_args, _ = make_report_batch(inst, meas, seed=1)
    progress["t"] = time.monotonic()
    print(f"[bench] backend={backend} shard: {time.time()-t0:.1f}s", file=sys.stderr, flush=True)

    verify_key = bytes(range(16))
    step = jax.jit(two_party_step(inst, verify_key))

    # warmup/compile
    t0 = time.time()
    out = jax.block_until_ready(step(*step_args))
    compile_s = time.time() - t0
    progress["t"] = time.monotonic()
    print(f"[bench] two_party_step compile+first: {compile_s:.1f}s", file=sys.stderr, flush=True)
    assert int(out[2]) == batch, f"bench reports rejected: {int(out[2])}/{batch}"

    t0 = time.time()
    for _ in range(args.iters):
        out = step(*step_args)
        progress["t"] = time.monotonic()
    jax.block_until_ready(out)
    elapsed = time.time() - t0
    progress["t"] = time.monotonic()
    device_rps = batch * args.iters / elapsed

    # host (CPU oracle) baseline, extrapolated per report
    host = prio3_host(inst)
    host_meas = random_measurements(inst, args.host_reports, rng)
    t0 = time.time()
    for i in range(args.host_reports):
        mi = host_meas[i]
        m = mi.tolist() if getattr(mi, "ndim", 0) else int(mi)
        nonce = bytes(16)
        public, (ls, hs) = host.shard(m, nonce)
        st0, ps0 = host.prepare_init(verify_key, 0, nonce, public, ls)
        st1, ps1 = host.prepare_init(verify_key, 1, nonce, public, hs)
        prep = host.prepare_shares_to_prep([ps0, ps1])
        host.prepare_next(st0, prep)
        host.prepare_next(st1, prep)
        progress["t"] = time.monotonic()
    host_s_per_report = (time.time() - t0) / args.host_reports
    # the host loop above includes shard(); prepare is ~2/3 of it — keep
    # the conservative (higher) host number by not discounting
    host_rps = 1.0 / host_s_per_report if host_s_per_report > 0 else float("inf")

    progress["done"] = True  # silences any re-armed watchdog timer
    if watchdog is not None:
        watchdog.cancel()
    if os.environ.get("JANUS_BENCH_CPU_FALLBACK") == "1":
        backend = f"{backend} (cpu fallback: accelerator stalled)"
    print(
        json.dumps(
            {
                "metric": f"prio3_{args.config}_two_party_prepare_accumulate",
                "value": round(device_rps, 2),
                "unit": "report_shares_per_sec_per_chip",
                "vs_baseline": round(device_rps / host_rps, 2),
                "backend": backend,
                "batch": batch,
                "iters": args.iters,
                "compile_s": round(compile_s, 1),
                "host_oracle_rps": round(host_rps, 3),
                "config": inst.to_dict(),
            }
        )
    )


if __name__ == "__main__":
    main()
