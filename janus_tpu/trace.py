"""Structured logging setup.

Equivalent of the reference's tracing subscriber installation
(aggregator/src/trace.rs:44-90): pretty or JSON line format, level
from config or the JANUS_LOG env var (the RUST_LOG analog). The
Chrome-trace/tokio-console layers map to the JAX profiler
(jax.profiler.trace emits Perfetto files); see docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from dataclasses import dataclass


@dataclass
class TraceConfiguration:
    """reference aggregator/src/trace.rs TraceConfiguration."""

    use_test_writer: bool = False
    force_json_output: bool = False
    level: str = "INFO"

    @classmethod
    def from_dict(cls, d: dict | None) -> "TraceConfiguration":
        d = d or {}
        return cls(
            use_test_writer=bool(d.get("use_test_writer", False)),
            force_json_output=bool(d.get("force_json_output", False)),
            level=str(d.get("level", "INFO")),
        )


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: TraceConfiguration | None = None) -> None:
    """Install the root logging handler (idempotent)."""
    config = config or TraceConfiguration()
    level = os.environ.get("JANUS_LOG", config.level).upper()
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    if config.force_json_output:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root.addHandler(handler)
