"""Structured logging + span tracing.

Equivalent of the reference's tracing subscriber installation
(aggregator/src/trace.rs:44-90): pretty or JSON line format, level
from config or the JANUS_LOG env var (the RUST_LOG analog), and a
**Chrome trace-file layer** (trace.rs:68-71): host-side spans —
request handlers, job steps, engine calls — written as Chrome
trace-event JSON, loadable in chrome://tracing or Perfetto alongside
the device-side `jax.profiler.trace` output (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import random
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

# span-id generation: uniqueness, not unpredictability (no urandom
# syscall); a module-level instance so the span() hot path pays neither
# an import nor the global-PRNG lock contention pattern
_span_rng = random.Random()


@dataclass
class TraceConfiguration:
    """reference aggregator/src/trace.rs TraceConfiguration."""

    use_test_writer: bool = False
    force_json_output: bool = False
    level: str = "INFO"
    # Path for host-side span output in Chrome trace-event format
    # (reference trace.rs:68-71 ChromeLayer); None disables. The
    # JANUS_CHROME_TRACE env var overrides.
    chrome_trace_file: str | None = None
    # OTLP/HTTP collector base endpoint (spans POST to /v1/traces,
    # metrics to /v1/metrics, JSON encoding) — the reference's
    # OpenTelemetry OTLP exporters (trace.rs:44-90, metrics.rs:53-80).
    # None disables; the JANUS_OTLP_ENDPOINT env var overrides.
    otlp_endpoint: str | None = None

    @classmethod
    def from_dict(cls, d: dict | None) -> "TraceConfiguration":
        d = d or {}
        return cls(
            use_test_writer=bool(d.get("use_test_writer", False)),
            force_json_output=bool(d.get("force_json_output", False)),
            level=str(d.get("level", "INFO")),
            chrome_trace_file=d.get("chrome_trace_file"),
            otlp_endpoint=d.get("otlp_endpoint"),
        )


class ChromeTraceWriter:
    """Streams complete ('X') trace events; the file is a JSON array
    readable by chrome://tracing and Perfetto even if the tail comma
    is left dangling on crash."""

    def __init__(self, path: str):
        self._f = open(path, "w")
        self._f.write("[\n")
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._closed = False

    def event(self, name: str, ts_us: float, dur_us: float, args: dict) -> None:
        doc = {
            "name": name,
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": self._pid,
            "tid": threading.get_ident() % 1_000_000,
            "args": args,
        }
        with self._lock:
            if self._closed:
                return  # a daemon thread's span outlived the writer
            try:
                self._f.write(json.dumps(doc) + ",\n")
                self._f.flush()
            except ValueError:
                self._closed = True

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.write("{}]\n")
                self._f.close()
            except ValueError:
                pass  # already closed


class OtlpExporter:
    """Dependency-free OTLP/HTTP exporter, JSON encoding (the OTLP/HTTP
    spec's JSON mapping of the protobufs): finished spans batch to
    {endpoint}/v1/traces, metrics-registry snapshots to /v1/metrics.
    The reference ships the same capability via the opentelemetry-otlp
    crate (aggregator/src/trace.rs:44-90, metrics.rs:53-80)."""

    def __init__(self, endpoint: str, service_name: str = "janus_tpu", flush_interval_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self._resource = {
            "attributes": [
                {"key": "service.name", "value": {"stringValue": service_name}},
                {"key": "process.pid", "value": {"intValue": str(os.getpid())}},
            ]
        }
        self._spans: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(flush_interval_s,), daemon=True
        )
        self._thread.start()
        atexit.register(self.shutdown)

    # --- span intake (called from span()'s exit path) ---
    def record_span(self, name, start_unix_ns, end_unix_ns, trace_id, span_id, parent_span_id, attrs):
        doc = {
            "traceId": _hex(trace_id, 32),
            "spanId": _hex(span_id, 16),
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_unix_ns),
            "endTimeUnixNano": str(end_unix_ns),
            "attributes": [
                {"key": k, "value": self._any_value(v)} for k, v in attrs.items()
            ],
        }
        if parent_span_id is not None:
            doc["parentSpanId"] = _hex(parent_span_id, 16)
        with self._lock:
            self._spans.append(doc)

    @staticmethod
    def _any_value(v):
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    # --- export ---
    def _post(self, path: str, doc: dict) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except Exception:
            logging.getLogger(__name__).debug("OTLP export to %s failed", path, exc_info=True)

    def flush(self) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if spans:
            self._post(
                "/v1/traces",
                {
                    "resourceSpans": [
                        {
                            "resource": self._resource,
                            "scopeSpans": [
                                {"scope": {"name": "janus_tpu"}, "spans": spans}
                            ],
                        }
                    ]
                },
            )
        metrics_doc = self._metrics_snapshot()
        if metrics_doc is not None:
            self._post("/v1/metrics", metrics_doc)

    def _metrics_snapshot(self) -> dict | None:
        from . import metrics as m

        now = str(time.time_ns())

        def attrs(labels):
            return [{"key": k, "value": {"stringValue": v}} for k, v in labels]

        out = []
        # metrics_list() copies under the registry lock: iterating
        # _metrics directly races a concurrent counter()/histogram()
        # registration ("dictionary changed size during iteration")
        for metric in m.REGISTRY.metrics_list():
            if isinstance(metric, m.Counter):
                with metric._lock:
                    items = sorted(metric._values.items())
                points = [
                    {"attributes": attrs(k), "timeUnixNano": now, "asDouble": v}
                    for k, v in items
                ]
                if points:
                    out.append(
                        {
                            "name": metric.name,
                            "sum": {
                                "dataPoints": points,
                                "aggregationTemporality": 2,  # CUMULATIVE
                                "isMonotonic": True,
                            },
                        }
                    )
            elif isinstance(metric, m.Gauge):
                with metric._lock:
                    items = sorted(metric._values.items())
                points = [
                    {"attributes": attrs(k), "timeUnixNano": now, "asDouble": v}
                    for k, v in items
                ]
                if points:
                    out.append({"name": metric.name, "gauge": {"dataPoints": points}})
            elif isinstance(metric, m.Histogram):
                points = []
                with metric._lock:
                    for key in sorted(metric._counts):
                        # OTLP bucket_counts are PER-BUCKET (unlike
                        # Prometheus's cumulative buckets); the last
                        # entry is the +Inf overflow
                        per_bucket = list(metric._counts[key])
                        overflow = metric._totals[key] - sum(per_bucket)
                        counts = [str(c) for c in per_bucket] + [str(overflow)]
                        points.append(
                            {
                                "attributes": attrs(key),
                                "timeUnixNano": now,
                                "count": str(metric._totals[key]),
                                "sum": metric._sums[key],
                                "bucketCounts": counts,
                                "explicitBounds": list(metric.buckets),
                            }
                        )
                if points:
                    out.append(
                        {
                            "name": metric.name,
                            "histogram": {"dataPoints": points, "aggregationTemporality": 2},
                        }
                    )
        if not out:
            return None
        return {
            "resourceMetrics": [
                {
                    "resource": self._resource,
                    "scopeMetrics": [{"scope": {"name": "janus_tpu"}, "metrics": out}],
                }
            ]
        }

    def _loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self.flush()
            except Exception:
                # the flusher must outlive any single bad export
                logging.getLogger(__name__).debug("OTLP flush failed", exc_info=True)

    def shutdown(self) -> None:
        self._stop.set()
        self.flush()


_chrome_writer: ChromeTraceWriter | None = None
_otlp_exporter: OtlpExporter | None = None


def install_otlp_export(endpoint: str, flush_interval_s: float = 5.0) -> OtlpExporter:
    """Install the process-wide OTLP exporter (spans + metrics)."""
    global _otlp_exporter
    if _otlp_exporter is not None:
        _otlp_exporter.shutdown()
    _otlp_exporter = OtlpExporter(endpoint, flush_interval_s=flush_interval_s)
    return _otlp_exporter


@contextmanager
def scoped_chrome_trace(path: str):
    """Temporarily route host spans to a fresh Chrome trace file (the
    /debug/profile capture window), restoring any configured writer on
    exit. Unlike install_chrome_trace the path is used verbatim — the
    caller owns the artifact name."""
    global _chrome_writer
    prev = _chrome_writer
    w = ChromeTraceWriter(path)
    _chrome_writer = w
    try:
        yield path
    finally:
        _chrome_writer = prev
        w.close()


def install_chrome_trace(path: str) -> None:
    """Install the process-wide span writer. The PID is embedded in the
    filename: several processes sharing one configured path (leader +
    helper on a host) must not truncate/interleave each other's files."""
    global _chrome_writer
    root, ext = os.path.splitext(path)
    path = f"{root}.{os.getpid()}{ext or '.json'}"
    if _chrome_writer is not None:
        _chrome_writer.close()
    _chrome_writer = ChromeTraceWriter(path)
    atexit.register(_chrome_writer.close)


# ---------------------------------------------------------------------------
# W3C traceparent propagation (the OTLP-shaped analog of the reference's
# OpenTelemetry layer, trace.rs:44-90): every span carries
# (trace_id, span_id, parent_span_id); the HTTP client attaches the
# current context as a `traceparent` header and the DAP server adopts an
# incoming one, so one trace stitches upload -> init -> continue across
# leader and helper processes.
# ---------------------------------------------------------------------------

import contextvars


# (trace_id, span_id) of the active span, per task/thread: ints for
# locally-generated ids (hex-formatted lazily by _hex), hex strings
# when adopted from an incoming traceparent header
_trace_ctx: contextvars.ContextVar[tuple[int | str, int | str] | None] = (
    contextvars.ContextVar("janus_trace_ctx", default=None)
)


def _hex(v, width: int) -> str:
    # ids live in the contextvar as ints (locally generated, formatted
    # lazily) or as hex strings (adopted from an incoming header)
    return v if isinstance(v, str) else f"{v:0{width}x}"


def current_traceparent() -> str | None:
    """W3C traceparent header for the active span, or None."""
    ctx = _trace_ctx.get()
    if ctx is None:
        return None
    return f"00-{_hex(ctx[0], 32)}-{_hex(ctx[1], 16)}-01"


_HEX_DIGITS = frozenset("0123456789abcdef")


def adopt_traceparent(header: str | None):
    """Enter the trace context of an incoming request (or clear it if
    the header is absent/malformed — the handler's span then starts a
    fresh trace as a true root, with no phantom parent). Returns a
    token for contextvars reset. Per W3C trace-context, ids must be
    lowercase hex and non-zero; anything else is treated as absent."""
    if header:
        parts = header.split("-")
        if (
            len(parts) == 4
            and len(parts[0]) == 2
            and len(parts[1]) == 32
            and len(parts[2]) == 16
            and len(parts[3]) == 2
            and set(parts[0]) <= _HEX_DIGITS
            and set(parts[1]) <= _HEX_DIGITS
            and set(parts[2]) <= _HEX_DIGITS
            and set(parts[3]) <= _HEX_DIGITS
            and parts[0] != "ff"  # W3C: version 0xff is invalid
            and set(parts[1]) != {"0"}
            and set(parts[2]) != {"0"}
        ):
            return _trace_ctx.set((parts[1], parts[2]))
    return _trace_ctx.set(None)


def reset_traceparent(token) -> None:
    _trace_ctx.reset(token)


def current_context():
    """Opaque trace context of the calling thread (for handing work to
    another thread — e.g. the ingest pipeline's stage workers — so their
    spans parent under the originating request's span)."""
    return _trace_ctx.get()


@contextmanager
def use_context(ctx):
    """Run the body under a trace context captured with
    current_context() on a different thread."""
    token = _trace_ctx.set(ctx)
    try:
        yield
    finally:
        _trace_ctx.reset(token)


# ---------------------------------------------------------------------------
# span -> metric bridge: a span NAME registered here records its
# duration into a histogram on exit, so the trace timeline and the
# Prometheus series measure the same boundaries by construction
# (registrations live next to the histogram definitions, metrics.py).
# Unregistered spans pay one dict lookup on exit.
# ---------------------------------------------------------------------------

_span_metrics: dict[str, tuple] = {}


def register_span_metric(
    span_name: str, histogram, labels: dict | None = None, arg_labels: tuple = ()
) -> None:
    """Record every exit of span `span_name` into `histogram`:
    `labels` attach verbatim; each name in `arg_labels` is copied from
    the span's kwargs when present (e.g. vdaf=...)."""
    _span_metrics[span_name] = (histogram, dict(labels or {}), tuple(arg_labels))


def _bridge_span(name: str, dur_s: float, args: dict) -> None:
    reg = _span_metrics.get(name)
    if reg is None:
        return
    hist, static, arg_labels = reg
    labels = dict(static)
    for k in arg_labels:
        v = args.get(k)
        if v is not None:
            labels[k] = str(v)
    hist.observe(dur_s, **labels)


@contextmanager
def span(name: str, **args):
    """Record a host-side span (event emission is a no-op unless a
    Chrome trace file is installed; the trace-context bookkeeping for
    traceparent propagation always runs — contextvar ops plus a PRNG
    draw, with hex formatting deferred to emission/header time so the
    untraced hot path stays near-free; ids need uniqueness, not
    unpredictability, so this is random.getrandbits, not a urandom
    syscall). Span names registered with register_span_metric also
    record their duration into the bound histogram on exit."""
    parent = _trace_ctx.get()
    trace_id = parent[0] if parent else _span_rng.getrandbits(128)
    span_id = _span_rng.getrandbits(64)
    token = _trace_ctx.set((trace_id, span_id))
    w = _chrome_writer
    ox = _otlp_exporter
    t0 = time.perf_counter_ns()
    e0 = time.time_ns() if ox is not None else 0
    try:
        yield
    finally:
        t1 = time.perf_counter_ns()
        _trace_ctx.reset(token)
        if _span_metrics:
            _bridge_span(name, (t1 - t0) / 1e9, args)
        if w is not None:
            w.event(
                name,
                t0 / 1000.0,
                (t1 - t0) / 1000.0,
                {
                    **args,
                    "trace_id": _hex(trace_id, 32),
                    "span_id": _hex(span_id, 16),
                    **({"parent_span_id": _hex(parent[1], 16)} if parent else {}),
                },
            )
        if ox is not None:
            ox.record_span(
                name, e0, e0 + (t1 - t0), trace_id, span_id,
                parent[1] if parent else None, args,
            )


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: TraceConfiguration | None = None) -> None:
    """Install the root logging handler (idempotent)."""
    config = config or TraceConfiguration()
    chrome = os.environ.get("JANUS_CHROME_TRACE", config.chrome_trace_file)
    if chrome:
        install_chrome_trace(chrome)
    otlp = os.environ.get("JANUS_OTLP_ENDPOINT", config.otlp_endpoint)
    if otlp:
        install_otlp_export(otlp)
    level = os.environ.get("JANUS_LOG", config.level).upper()
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    if config.force_json_output:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root.addHandler(handler)
