"""Structured logging + span tracing.

Equivalent of the reference's tracing subscriber installation
(aggregator/src/trace.rs:44-90): pretty or JSON line format, level
from config or the JANUS_LOG env var (the RUST_LOG analog), and a
**Chrome trace-file layer** (trace.rs:68-71): host-side spans —
request handlers, job steps, engine calls — written as Chrome
trace-event JSON, loadable in chrome://tracing or Perfetto alongside
the device-side `jax.profiler.trace` output (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import atexit
import collections
import json
import logging
import math
import os
import random
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

# span-id generation: uniqueness, not unpredictability (no urandom
# syscall); a module-level instance so the span() hot path pays neither
# an import nor the global-PRNG lock contention pattern
_span_rng = random.Random()


@dataclass
class TraceConfiguration:
    """reference aggregator/src/trace.rs TraceConfiguration."""

    use_test_writer: bool = False
    force_json_output: bool = False
    level: str = "INFO"
    # Path for host-side span output in Chrome trace-event format
    # (reference trace.rs:68-71 ChromeLayer); None disables. The
    # JANUS_CHROME_TRACE env var overrides.
    chrome_trace_file: str | None = None
    # OTLP/HTTP collector base endpoint (spans POST to /v1/traces,
    # metrics to /v1/metrics, JSON encoding) — the reference's
    # OpenTelemetry OTLP exporters (trace.rs:44-90, metrics.rs:53-80).
    # None disables; the JANUS_OTLP_ENDPOINT env var overrides.
    otlp_endpoint: str | None = None

    @classmethod
    def from_dict(cls, d: dict | None) -> "TraceConfiguration":
        d = d or {}
        return cls(
            use_test_writer=bool(d.get("use_test_writer", False)),
            force_json_output=bool(d.get("force_json_output", False)),
            level=str(d.get("level", "INFO")),
            chrome_trace_file=d.get("chrome_trace_file"),
            otlp_endpoint=d.get("otlp_endpoint"),
        )


class ChromeTraceWriter:
    """Streams complete ('X') trace events; the file is a JSON array
    readable by chrome://tracing and Perfetto even if the tail comma
    is left dangling on crash.

    Events are buffered and flushed on a size/time threshold (a daemon
    flusher covers the idle case — a burst followed by silence still
    reaches disk within FLUSH_INTERVAL_S) and on close() — the previous
    per-event write+flush cost ~45 µs/span (bench `tracing_overhead`,
    PR 3), dominating the span hot path. Crash tolerance trades down
    accordingly: at most FLUSH_BYTES / FLUSH_INTERVAL_S of tail spans
    can be lost with the process (the flight recorder keeps them in
    memory regardless)."""

    FLUSH_BYTES = 64 * 1024
    FLUSH_INTERVAL_S = 1.0

    def __init__(self, path: str, flush_interval_s: float | None = None):
        self._f = open(path, "w")
        self._f.write("[\n")
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._closed = False
        self._buf: list[str] = []
        self._buf_bytes = 0
        self._last_flush = time.monotonic()
        self._flush_interval = (
            flush_interval_s if flush_interval_s is not None else self.FLUSH_INTERVAL_S
        )
        self._stop_flusher = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, name="chrome-trace-flush", daemon=True
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        while not self._stop_flusher.wait(self._flush_interval):
            with self._lock:
                if self._closed:
                    return
                if self._buf:
                    self._flush_locked()

    def event(self, name: str, ts_us: float, dur_us: float, args: dict) -> None:
        doc = {
            "name": name,
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": self._pid,
            "tid": threading.get_ident() % 1_000_000,
            "args": args,
        }
        line = json.dumps(doc) + ",\n"
        with self._lock:
            if self._closed:
                return  # a daemon thread's span outlived the writer
            self._buf.append(line)
            self._buf_bytes += len(line)
            now = time.monotonic()
            if (
                self._buf_bytes >= self.FLUSH_BYTES
                or now - self._last_flush >= self._flush_interval
            ):
                self._flush_locked(now)

    def _flush_locked(self, now: float | None = None) -> None:
        try:
            self._f.write("".join(self._buf))
            self._f.flush()
        except ValueError:
            self._closed = True
        self._buf.clear()
        self._buf_bytes = 0
        self._last_flush = now if now is not None else time.monotonic()

    def close(self) -> None:
        self._stop_flusher.set()
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._closed = True
            try:
                self._f.write("{}]\n")
                self._f.close()
            except ValueError:
                pass  # already closed


class OtlpExporter:
    """Dependency-free OTLP/HTTP exporter, JSON encoding (the OTLP/HTTP
    spec's JSON mapping of the protobufs): finished spans batch to
    {endpoint}/v1/traces, metrics-registry snapshots to /v1/metrics.
    The reference ships the same capability via the opentelemetry-otlp
    crate (aggregator/src/trace.rs:44-90, metrics.rs:53-80)."""

    # Bound on spans buffered between flushes: a down collector must
    # not let the buffer grow with load for a whole flush interval;
    # past the cap the OLDEST spans drop (counted by
    # janus_otlp_spans_dropped_total) so the freshest context survives.
    MAX_BUFFERED_SPANS = 4096

    def __init__(self, endpoint: str, service_name: str = "janus_tpu", flush_interval_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self._resource = {
            "attributes": [
                {"key": "service.name", "value": {"stringValue": service_name}},
                {"key": "process.pid", "value": {"intValue": str(os.getpid())}},
            ]
        }
        # process-wide resource attributes set before this exporter
        # existed (fleet replica identity) still apply
        self.apply_resource_attributes(resource_attributes())
        self._spans: list[dict] = []
        self._lock = threading.Lock()
        # a hung collector must not stall the flush loop past its own
        # interval (the old fixed 10 s timeout could back the loop up
        # 2x per flush at the default 5 s interval)
        self._post_timeout = max(0.1, min(float(flush_interval_s), 5.0))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(flush_interval_s,), daemon=True
        )
        self._thread.start()
        atexit.register(self.shutdown)

    def apply_resource_attributes(self, attrs: dict) -> None:
        """Merge process-wide resource attributes (replica identity)
        into this exporter's OTLP resource, last-write-wins by key.
        Copy-on-write: the flush thread serializes self._resource
        concurrently, so the merged document is built aside and
        swapped in with one atomic reference assignment — never
        mutated in place under a running json.dumps."""
        merged = [dict(ent) for ent in self._resource["attributes"]]
        for k, v in attrs.items():
            for ent in merged:
                if ent["key"] == k:
                    ent["value"] = {"stringValue": str(v)}
                    break
            else:
                merged.append({"key": k, "value": {"stringValue": str(v)}})
        self._resource = {"attributes": merged}

    # --- span intake (called from span()'s exit path) ---
    def record_span(self, name, start_unix_ns, end_unix_ns, trace_id, span_id, parent_span_id, attrs):
        doc = {
            "traceId": _hex(trace_id, 32),
            "spanId": _hex(span_id, 16),
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_unix_ns),
            "endTimeUnixNano": str(end_unix_ns),
            "attributes": [
                {"key": k, "value": self._any_value(v)} for k, v in attrs.items()
            ],
        }
        if parent_span_id is not None:
            doc["parentSpanId"] = _hex(parent_span_id, 16)
        dropped = 0
        with self._lock:
            self._spans.append(doc)
            overflow = len(self._spans) - self.MAX_BUFFERED_SPANS
            if overflow > 0:
                del self._spans[:overflow]
                dropped = overflow
        if dropped:
            from . import metrics

            metrics.otlp_spans_dropped_total.add(dropped)

    @staticmethod
    def _any_value(v):
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    # --- export ---
    def _post(self, path: str, doc: dict) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self._post_timeout) as resp:
                resp.read()
        except Exception:
            logging.getLogger(__name__).debug("OTLP export to %s failed", path, exc_info=True)

    def flush(self) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if spans:
            self._post(
                "/v1/traces",
                {
                    "resourceSpans": [
                        {
                            "resource": self._resource,
                            "scopeSpans": [
                                {"scope": {"name": "janus_tpu"}, "spans": spans}
                            ],
                        }
                    ]
                },
            )
        metrics_doc = self._metrics_snapshot()
        if metrics_doc is not None:
            self._post("/v1/metrics", metrics_doc)

    def _metrics_snapshot(self) -> dict | None:
        from . import metrics as m

        now = str(time.time_ns())

        def attrs(labels):
            return [{"key": k, "value": {"stringValue": v}} for k, v in labels]

        out = []
        # metrics_list() copies under the registry lock: iterating
        # _metrics directly races a concurrent counter()/histogram()
        # registration ("dictionary changed size during iteration")
        for metric in m.REGISTRY.metrics_list():
            if isinstance(metric, m.Counter):
                with metric._lock:
                    items = sorted(metric._values.items())
                points = [
                    {"attributes": attrs(k), "timeUnixNano": now, "asDouble": v}
                    for k, v in items
                ]
                if points:
                    out.append(
                        {
                            "name": metric.name,
                            "sum": {
                                "dataPoints": points,
                                "aggregationTemporality": 2,  # CUMULATIVE
                                "isMonotonic": True,
                            },
                        }
                    )
            elif isinstance(metric, m.Gauge):
                with metric._lock:
                    items = sorted(metric._values.items())
                points = [
                    {"attributes": attrs(k), "timeUnixNano": now, "asDouble": v}
                    for k, v in items
                ]
                if points:
                    out.append({"name": metric.name, "gauge": {"dataPoints": points}})
            elif isinstance(metric, m.Histogram):
                points = []
                with metric._lock:
                    for key in sorted(metric._counts):
                        # OTLP bucket_counts are PER-BUCKET (unlike
                        # Prometheus's cumulative buckets); the last
                        # entry is the +Inf overflow
                        per_bucket = list(metric._counts[key])
                        overflow = metric._totals[key] - sum(per_bucket)
                        counts = [str(c) for c in per_bucket] + [str(overflow)]
                        points.append(
                            {
                                "attributes": attrs(key),
                                "timeUnixNano": now,
                                "count": str(metric._totals[key]),
                                "sum": metric._sums[key],
                                "bucketCounts": counts,
                                "explicitBounds": list(metric.buckets),
                            }
                        )
                if points:
                    out.append(
                        {
                            "name": metric.name,
                            "histogram": {"dataPoints": points, "aggregationTemporality": 2},
                        }
                    )
        if not out:
            return None
        return {
            "resourceMetrics": [
                {
                    "resource": self._resource,
                    "scopeMetrics": [{"scope": {"name": "janus_tpu"}, "metrics": out}],
                }
            ]
        }

    def _loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self.flush()
            except Exception:
                # the flusher must outlive any single bad export
                logging.getLogger(__name__).debug("OTLP flush failed", exc_info=True)

    def shutdown(self) -> None:
        self._stop.set()
        self.flush()


_chrome_writer: ChromeTraceWriter | None = None
_otlp_exporter: OtlpExporter | None = None


def install_otlp_export(endpoint: str, flush_interval_s: float = 5.0) -> OtlpExporter:
    """Install the process-wide OTLP exporter (spans + metrics)."""
    global _otlp_exporter
    if _otlp_exporter is not None:
        _otlp_exporter.shutdown()
    _otlp_exporter = OtlpExporter(endpoint, flush_interval_s=flush_interval_s)
    return _otlp_exporter


@contextmanager
def scoped_chrome_trace(path: str):
    """Temporarily route host spans to a fresh Chrome trace file (the
    /debug/profile capture window), restoring any configured writer on
    exit. Unlike install_chrome_trace the path is used verbatim — the
    caller owns the artifact name."""
    global _chrome_writer
    prev = _chrome_writer
    w = ChromeTraceWriter(path)
    _chrome_writer = w
    try:
        yield path
    finally:
        _chrome_writer = prev
        w.close()


def install_chrome_trace(path: str) -> None:
    """Install the process-wide span writer. The PID is embedded in the
    filename: several processes sharing one configured path (leader +
    helper on a host) must not truncate/interleave each other's files."""
    global _chrome_writer
    root, ext = os.path.splitext(path)
    path = f"{root}.{os.getpid()}{ext or '.json'}"
    if _chrome_writer is not None:
        _chrome_writer.close()
    _chrome_writer = ChromeTraceWriter(path)
    atexit.register(_chrome_writer.close)


# ---------------------------------------------------------------------------
# W3C traceparent propagation (the OTLP-shaped analog of the reference's
# OpenTelemetry layer, trace.rs:44-90): every span carries
# (trace_id, span_id, parent_span_id); the HTTP client attaches the
# current context as a `traceparent` header and the DAP server adopts an
# incoming one, so one trace stitches upload -> init -> continue across
# leader and helper processes.
# ---------------------------------------------------------------------------

import contextvars


# (trace_id, span_id) of the active span, per task/thread: ints for
# locally-generated ids (hex-formatted lazily by _hex), hex strings
# when adopted from an incoming traceparent header
_trace_ctx: contextvars.ContextVar[tuple[int | str, int | str] | None] = (
    contextvars.ContextVar("janus_trace_ctx", default=None)
)


def _hex(v, width: int) -> str:
    # ids live in the contextvar as ints (locally generated, formatted
    # lazily) or as hex strings (adopted from an incoming header)
    return v if isinstance(v, str) else f"{v:0{width}x}"


def current_traceparent() -> str | None:
    """W3C traceparent header for the active span, or None."""
    ctx = _trace_ctx.get()
    if ctx is None:
        return None
    return f"00-{_hex(ctx[0], 32)}-{_hex(ctx[1], 16)}-01"


_HEX_DIGITS = frozenset("0123456789abcdef")


def _parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """(trace_id, span_id) from a W3C traceparent, or None when the
    header is absent/malformed. Per the spec, ids must be lowercase hex
    and non-zero, the version 2 hex digits != 'ff', flags 2 hex."""
    if not header:
        return None
    parts = header.split("-")
    if (
        len(parts) == 4
        and len(parts[0]) == 2
        and len(parts[1]) == 32
        and len(parts[2]) == 16
        and len(parts[3]) == 2
        and set(parts[0]) <= _HEX_DIGITS
        and set(parts[1]) <= _HEX_DIGITS
        and set(parts[2]) <= _HEX_DIGITS
        and set(parts[3]) <= _HEX_DIGITS
        and parts[0] != "ff"  # W3C: version 0xff is invalid
        and set(parts[1]) != {"0"}
        and set(parts[2]) != {"0"}
    ):
        return parts[1], parts[2]
    return None


def trace_id_of(header: str | None) -> str | None:
    """Validated trace id of a traceparent header (the persisted
    trace_context column), or None — the one place that parses it for
    display/linking (driver linked_traces, bench, tests)."""
    parsed = _parse_traceparent(header)
    return parsed[0] if parsed else None


def adopt_traceparent(header: str | None):
    """Enter the trace context of an incoming request (or clear it if
    the header is absent/malformed — the handler's span then starts a
    fresh trace as a true root, with no phantom parent). Returns a
    token for contextvars reset."""
    parsed = _parse_traceparent(header)
    if parsed is not None:
        return _trace_ctx.set(parsed)
    return _trace_ctx.set(None)


def reset_traceparent(token) -> None:
    _trace_ctx.reset(token)


@contextmanager
def use_traceparent(header: str | None):
    """Run the body under a PERSISTED trace context (the datastore
    `trace_context` column on aggregation/collection jobs): spans opened
    inside become children of the span that created the job — across
    processes and across driver restarts, because the header round-trips
    through the database rather than living in any process. A falsy
    header is a no-op (the caller's ambient context is preserved), so
    rows written before the column existed keep today's behavior."""
    if not header:
        yield
        return
    token = adopt_traceparent(header)
    try:
        yield
    finally:
        _trace_ctx.reset(token)


def current_context():
    """Opaque trace context of the calling thread (for handing work to
    another thread — e.g. the ingest pipeline's stage workers — so their
    spans parent under the originating request's span)."""
    return _trace_ctx.get()


@contextmanager
def use_context(ctx):
    """Run the body under a trace context captured with
    current_context() on a different thread."""
    token = _trace_ctx.set(ctx)
    try:
        yield
    finally:
        _trace_ctx.reset(token)


# ---------------------------------------------------------------------------
# span -> metric bridge: a span NAME registered here records its
# duration into a histogram on exit, so the trace timeline and the
# Prometheus series measure the same boundaries by construction
# (registrations live next to the histogram definitions, metrics.py).
# Unregistered spans pay one dict lookup on exit.
# ---------------------------------------------------------------------------

_span_metrics: dict[str, tuple] = {}

# span name -> [fn(dur_s, args)] side-channel hooks: the device cost
# ledger (janus_tpu/profiler.py) attributes the engine put/fetch spans'
# wall time to its h2d/d2h phases through these, so the ledger and the
# trace timeline measure the same boundaries by construction. A hook
# must never raise into the span exit path.
_span_hooks: dict[str, list] = {}


def register_span_metric(
    span_name: str, histogram, labels: dict | None = None, arg_labels: tuple = ()
) -> None:
    """Record every exit of span `span_name` into `histogram`:
    `labels` attach verbatim; each name in `arg_labels` is copied from
    the span's kwargs when present (e.g. vdaf=...)."""
    _span_metrics[span_name] = (histogram, dict(labels or {}), tuple(arg_labels))


def register_span_hook(span_name: str, fn) -> None:
    """Call `fn(dur_s, args)` on every exit of span `span_name`
    (in addition to any register_span_metric binding)."""
    _span_hooks.setdefault(span_name, []).append(fn)


def _bridge_span(name: str, dur_s: float, args: dict, trace_id=None) -> None:
    hooks = _span_hooks.get(name)
    if hooks is not None:
        for fn in hooks:
            try:
                fn(dur_s, args)
            except Exception:
                logging.getLogger(__name__).exception(
                    "span hook for %s failed", name
                )
    reg = _span_metrics.get(name)
    if reg is None:
        return
    hist, static, arg_labels = reg
    labels = dict(static)
    for k in arg_labels:
        v = args.get(k)
        if v is not None:
            labels[k] = str(v)
    # the exiting span's trace id rides the histogram sample as an
    # OpenMetrics exemplar (metrics.Histogram.observe), so a latency
    # bucket jump resolves to a concrete /debug/traces capture
    hist.observe(dur_s, exemplar_trace_id=trace_id, **labels)


# ---------------------------------------------------------------------------
# Flight recorder: an always-on, bounded, in-process ring of completed
# spans. Unlike the Chrome/OTLP writers (opt-in, file/network), this is
# always armed, so "where did THIS report's time go" is answerable
# after the fact without having pre-arranged a capture window:
#
#   - a deque ring of the last N completed spans (GIL-atomic appends —
#     no lock on the ring itself),
#   - per-name streaming latency digests (log2-microsecond buckets ->
#     p50/p95/p99 without storing samples),
#   - slow-op capture: when a ROOT span exceeds its per-name threshold,
#     the whole span tree still present in the ring is retained in a
#     separate bounded buffer (children complete before their root, so
#     the tree is intact unless ring churn evicted it first).
#
# Served as GET /debug/traces on every binary's health listener and as
# a /statusz section (binary_utils.HealthServer).
# ---------------------------------------------------------------------------

# log2(microsecond) duration buckets: index i covers [2^i, 2^(i+1)) µs;
# 40 buckets reach ~12.7 days — far past any span this system emits
_DIGEST_BUCKETS = 40


class _NameDigest:
    __slots__ = ("count", "errors", "sum_s", "buckets")

    def __init__(self):
        self.count = 0
        self.errors = 0
        self.sum_s = 0.0
        self.buckets = [0] * _DIGEST_BUCKETS

    def observe(self, dur_s: float, error: bool) -> None:
        us = dur_s * 1e6
        idx = 0 if us < 2.0 else min(int(us).bit_length() - 1, _DIGEST_BUCKETS - 1)
        self.buckets[idx] += 1
        self.count += 1
        self.sum_s += dur_s
        if error:
            self.errors += 1

    def quantile(self, q: float) -> float:
        """Upper bound (seconds) of the bucket holding the q-quantile."""
        if self.count == 0:
            return 0.0
        target = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= target:
                return (1 << (i + 1)) / 1e6
        return (1 << _DIGEST_BUCKETS) / 1e6

    def doc(self) -> dict:
        return {
            "count": self.count,
            "errors": self.errors,
            "mean_s": round(self.sum_s / self.count, 6) if self.count else 0.0,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }


class FlightRecorder:
    """See the section comment above. `capacity` and the default slow
    threshold come from JANUS_FLIGHT_RECORDER_SPANS /
    JANUS_SLOW_TRACE_THRESHOLD_S when not passed explicitly."""

    def __init__(
        self,
        capacity: int | None = None,
        slow_capacity: int = 8,
        slow_threshold_s: float | None = None,
    ):
        if capacity is None:
            capacity = int(os.environ.get("JANUS_FLIGHT_RECORDER_SPANS", "512"))
        self.capacity = max(16, capacity)
        if slow_threshold_s is None:
            slow_threshold_s = float(
                os.environ.get("JANUS_SLOW_TRACE_THRESHOLD_S", "1.0")
            )
        self.default_slow_threshold_s = slow_threshold_s
        # ring entries: (name, trace_id, span_id, parent_span_id,
        # start_unix_ns, dur_s, args, error) — ids raw (int | hex str),
        # hex-formatted only at snapshot time to keep record() cheap
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._slow: collections.deque = collections.deque(maxlen=max(1, slow_capacity))
        self._slow_thresholds: dict[str, float] = {}
        self._digests: dict[str, _NameDigest] = {}
        # guards digests + slow capture only; the ring rides the GIL
        self._lock = threading.Lock()
        self._recorded = 0

    def set_slow_threshold(self, name: str, seconds: float) -> None:
        """Per-root-span-name slow-capture threshold: a root span of
        `name` lasting >= `seconds` captures its tree. 0 captures every
        root span of that name (tests); negative disables the name."""
        self._slow_thresholds[name] = float(seconds)

    def record(
        self, name, trace_id, span_id, parent_span_id, start_unix_ns, dur_s, args, error
    ) -> None:
        entry = (name, trace_id, span_id, parent_span_id, start_unix_ns, dur_s, args, error)
        self._ring.append(entry)
        with self._lock:
            self._recorded += 1
            digest = self._digests.get(name)
            if digest is None:
                digest = self._digests[name] = _NameDigest()
            digest.observe(dur_s, error is not None)
            # slow capture triggers on LOCAL roots: spans with no parent
            # at all, or whose parent is remote (hex-string ids adopted
            # from a traceparent header / persisted trace_context —
            # locally generated parents are ints). Without the latter, a
            # driver step's work spans — all children of the persisted
            # creator span — could never trigger capture in THIS process.
            if parent_span_id is None or isinstance(parent_span_id, str):
                threshold = self._slow_thresholds.get(name, self.default_slow_threshold_s)
                if 0 < threshold <= dur_s or (threshold == 0.0 and name in self._slow_thresholds):
                    # whole tree still in the ring (children completed
                    # first); list() snapshots the deque atomically
                    tree = [e for e in list(self._ring) if e[1] == trace_id]
                    self._slow.append(
                        {
                            "root": name,
                            "trace_id": _hex(trace_id, 32),
                            "duration_s": round(dur_s, 6),
                            "threshold_s": threshold,
                            "captured_unix_ns": start_unix_ns + int(dur_s * 1e9),
                            "spans": [self._entry_doc(e) for e in tree],
                        }
                    )

    @staticmethod
    def _entry_doc(entry) -> dict:
        name, trace_id, span_id, parent, start_ns, dur_s, args, error = entry
        doc = {
            "name": name,
            "trace_id": _hex(trace_id, 32),
            "span_id": _hex(span_id, 16),
            "start_unix_ns": str(start_ns),
            "duration_s": round(dur_s, 6),
        }
        if parent is not None:
            doc["parent_span_id"] = _hex(parent, 16)
        if args:
            doc["args"] = {k: v for k, v in args.items()}
        if error is not None:
            doc["error"] = error
        return doc

    def snapshot(self, recent_limit: int = 100) -> dict:
        """The /debug/traces payload: recent spans (newest last), the
        captured slow traces, and the per-name latency digests. Every
        span implicitly carries the process resource attributes
        (replica identity in a fleet) — surfaced once at the top, OTLP
        resource-semantics style, instead of per span."""
        recent = list(self._ring)[-recent_limit:] if recent_limit > 0 else []
        with self._lock:
            digests = {name: d.doc() for name, d in sorted(self._digests.items())}
            slow = list(self._slow)
        return {
            "recorded_total": self._recorded,
            "capacity": self.capacity,
            "default_slow_threshold_s": self.default_slow_threshold_s,
            "resource": dict(_resource_attributes),
            "recent": [self._entry_doc(e) for e in recent],
            "slow_traces": slow,
            "digests": digests,
        }

    def status(self) -> dict:
        """The compact /statusz section (no span bodies)."""
        with self._lock:
            digests = {name: d.doc() for name, d in sorted(self._digests.items())}
            slow = len(self._slow)
        return {
            "recorded_total": self._recorded,
            "ring": len(self._ring),
            "capacity": self.capacity,
            "slow_traces_captured": slow,
            "default_slow_threshold_s": self.default_slow_threshold_s,
            "names": digests,
        }


_flight_recorder = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-wide always-on recorder."""
    return _flight_recorder


# Process-wide resource attributes (OTLP resource semantics: they apply
# to every span this process emits). janus_main stamps the fleet
# replica identity here so traces from N replicas over one datastore
# stay attributable; /debug/traces surfaces them in its snapshot and
# the OTLP exporter merges them into resourceSpans.resource.
_resource_attributes: dict[str, str] = {}


def set_resource_attributes(**attrs) -> None:
    """Set/overwrite process-wide trace resource attributes (e.g.
    replica="replica-3"). Applied to the flight-recorder snapshot and
    to any OTLP exporter installed now or later."""
    for k, v in attrs.items():
        _resource_attributes[str(k)] = str(v)
    exporter = _otlp_exporter
    if exporter is not None:
        exporter.apply_resource_attributes(_resource_attributes)


def resource_attributes() -> dict:
    return dict(_resource_attributes)


# span-error counter resolved lazily (importing metrics at module level
# would cycle: metrics.py binds span names via register_span_metric at
# its import tail)
_span_errors_counter = None


def _count_span_error(name: str) -> None:
    global _span_errors_counter
    c = _span_errors_counter
    if c is None:
        from . import metrics

        c = _span_errors_counter = metrics.span_errors_total
    c.add(name=name)


@contextmanager
def span(name: str, **args):
    """Record a host-side span. The always-on flight recorder and the
    trace-context bookkeeping for traceparent propagation run on every
    span (contextvar ops, a PRNG draw, a deque append and a digest
    update — measured by the bench `tracing_overhead` phase; hex
    formatting is deferred to emission/snapshot time; ids need
    uniqueness, not unpredictability, so this is random.getrandbits,
    not a urandom syscall). Chrome/OTLP emission additionally runs when
    those writers are installed. Span names registered with
    register_span_metric also record their duration into the bound
    histogram on exit. An exception exiting the span is recorded as an
    `error=<ExcType>` attribute on every emitted event and counted in
    janus_span_errors_total{name} — then re-raised."""
    parent = _trace_ctx.get()
    trace_id = parent[0] if parent else _span_rng.getrandbits(128)
    span_id = _span_rng.getrandbits(64)
    token = _trace_ctx.set((trace_id, span_id))
    w = _chrome_writer
    ox = _otlp_exporter
    t0 = time.perf_counter_ns()
    e0 = time.time_ns()
    err_name = None
    try:
        yield
    except BaseException as e:
        err_name = type(e).__name__
        raise
    finally:
        t1 = time.perf_counter_ns()
        _trace_ctx.reset(token)
        if err_name is not None:
            args["error"] = err_name  # kwargs dict is per-call: safe to mutate
            _count_span_error(name)
        dur_s = (t1 - t0) / 1e9
        if _span_metrics or _span_hooks:
            _bridge_span(name, dur_s, args, trace_id)
        _flight_recorder.record(
            name, trace_id, span_id, parent[1] if parent else None,
            e0, dur_s, args, err_name,
        )
        if w is not None:
            w.event(
                name,
                t0 / 1000.0,
                (t1 - t0) / 1000.0,
                {
                    **args,
                    "trace_id": _hex(trace_id, 32),
                    "span_id": _hex(span_id, 16),
                    **({"parent_span_id": _hex(parent[1], 16)} if parent else {}),
                },
            )
        if ox is not None:
            ox.record_span(
                name, e0, e0 + (t1 - t0), trace_id, span_id,
                parent[1] if parent else None, args,
            )


def record_operation(name: str, dur_s: float, **args) -> None:
    """Feed a completed cross-thread operation into the flight
    recorder's per-name digests (and the span->metric bridge) without a
    live span context. The step pipeline uses it for the end-to-end
    "job.step" duration: the stages run on different threads, so no
    single span() block can cover the whole step, but the digest —
    which the bench's served phase reads for the p50/p95 aggregation-
    job-step SLO — must still see one observation per stepped job."""
    trace_id = _span_rng.getrandbits(128)
    if _span_metrics or _span_hooks:
        # the synthesized trace id still resolves: the recorder ring
        # entry below carries the same id, so a bridged exemplar from a
        # cross-thread operation links to its /debug/traces record
        _bridge_span(name, dur_s, args, trace_id)
    _flight_recorder.record(
        name,
        trace_id,
        _span_rng.getrandbits(64),
        None,
        time.time_ns() - int(dur_s * 1e9),
        dur_s,
        args,
        args.get("error"),
    )


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        # correlate structured logs with traces: a log line emitted
        # under an active span carries its ids (docs/OBSERVABILITY.md)
        ctx = _trace_ctx.get()
        if ctx is not None:
            doc["trace_id"] = _hex(ctx[0], 32)
            doc["span_id"] = _hex(ctx[1], 16)
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: TraceConfiguration | None = None) -> None:
    """Install the root logging handler (idempotent)."""
    config = config or TraceConfiguration()
    chrome = os.environ.get("JANUS_CHROME_TRACE", config.chrome_trace_file)
    if chrome:
        install_chrome_trace(chrome)
    otlp = os.environ.get("JANUS_OTLP_ENDPOINT", config.otlp_endpoint)
    if otlp:
        install_otlp_export(otlp)
    level = os.environ.get("JANUS_LOG", config.level).upper()
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    if config.force_json_output:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root.addHandler(handler)


# /statusz section: the flight recorder's compact summary on every
# binary (the full payload is GET /debug/traces on the health listener)
from .statusz import register_status_provider as _register_status_provider

_register_status_provider("flight_recorder", lambda: _flight_recorder.status())
