"""/statusz provider registry: one JSON/HTML snapshot per process.

The reference exposes operational state through its aggregator-api and
OTel resources; here every subsystem that owns interesting state
registers a named provider callable and the health listener
(binary_utils.HealthServer) renders the union at GET /statusz —
build/process info, configured tasks, engine-cache state (bucket caps,
backend, OOM history), ingest pipeline occupancy, and the job backlog
from the health sampler.

Providers must be cheap and must never raise into the handler: a
provider error renders as {"error": ...} under its section instead of
failing the whole snapshot.
"""

from __future__ import annotations

import html
import json
import threading
import time

_lock = threading.Lock()
_providers: dict[str, object] = {}


def register_status_provider(name: str, fn) -> None:
    """Register (or replace) the section `name`; `fn()` returns any
    JSON-serializable value."""
    with _lock:
        _providers[name] = fn


def unregister_status_provider(name: str, fn=None) -> None:
    """Remove the section `name`. With `fn`, remove only if it is still
    the registered provider — a closing subsystem must not tear down a
    successor's registration (latest registration wins)."""
    with _lock:
        if fn is None or _providers.get(name) is fn:
            _providers.pop(name, None)


def status_snapshot() -> dict:
    with _lock:
        providers = dict(_providers)
    out: dict = {"generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    for name, fn in sorted(providers.items()):
        try:
            out[name] = fn()
        except Exception as e:  # a broken provider must not kill /statusz
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def render_statusz_html(snapshot: dict) -> str:
    """Minimal dependency-free HTML view of the snapshot (one <section>
    per provider, pretty-printed JSON bodies). Every provider-supplied
    string — section names and values alike — must pass through
    html.escape before it reaches the page: hostile label values (a
    task id carrying <script>) render inert, pinned by
    tests/test_metrics_exposition.py::test_statusz_html_escapes_hostile_values."""
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        "<title>janus_tpu statusz</title>",
        "<style>body{font-family:monospace;margin:2em;}h2{border-bottom:1px solid #999;}"
        "pre{background:#f4f4f4;padding:0.6em;overflow-x:auto;}</style>",
        "</head><body><h1>janus_tpu /statusz</h1>",
    ]
    for name, value in snapshot.items():
        if name == "generated_at":
            parts.append(f"<p>generated at {html.escape(str(value))}</p>")
            continue
        body = html.escape(json.dumps(value, indent=2, default=str))
        parts.append(f"<h2>{html.escape(name)}</h2><pre>{body}</pre>")
    parts.append("</body></html>")
    return "".join(parts)
