"""Admission-controlled, bounded upload ingest (docs/INGEST.md).

The serving front door for client report uploads: an
AdmissionController (token buckets + queue-depth watermarks, shedding
with 429 + Retry-After in configured priority order) in front of an
IngestPipeline (decode → parallel HPKE-decrypt pool → validation →
group commit through the ReportWriteBatcher)."""

from .admission import AdmissionConfig, AdmissionController, ShedError, TokenBucket
from .journal import JournalFull, JournalReplayer, UploadJournal
from .pipeline import IngestPipeline, UploadTicket, default_decrypt_workers

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "IngestPipeline",
    "JournalFull",
    "JournalReplayer",
    "ShedError",
    "TokenBucket",
    "UploadJournal",
    "UploadTicket",
    "default_decrypt_workers",
]
