"""Admission control for the DAP front door.

The upload route is the scale-out dimension of a DAP deployment (the
original Prio paper frames client report submission as the dimension
that grows with the user base), and the serving cost of a report is
paid server-side (TAPAS: two-server aggregation lives or dies on
per-report server cost under asymmetric load). An aggregator above
capacity must answer a cheap, honest `429 + Retry-After` — not grow
threads without bound and thrash the GIL on HPKE opens.

Two admission signals, evaluated per request before any crypto work:

* **Token buckets** per route class (`upload`, `aggregate`): a
  configured sustained rate plus burst. Rate 0 disables the bucket
  (unlimited).
* **Queue-depth watermarks** derived from the ingest pipeline's
  bounded stage queues: when pipeline occupancy crosses a class's
  watermark, that class sheds. Watermarks are spaced by the configured
  shed priority order — the first class (client uploads by default)
  sheds at `queue_high_watermark`, later classes (the
  aggregator-to-aggregator steps that finish work already admitted)
  shed only as the queue approaches full.

Shedding raises `ShedError`, which the HTTP layer maps to a 429
problem document with a `Retry-After` header and counts in
`janus_upload_shed_total`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


class ShedError(Exception):
    """Request refused by admission control. `status` is the HTTP
    answer: 429 for capacity sheds (try again soon), 503 for
    availability sheds (datastore down, journal full — the server,
    not the client, is the problem); both carry Retry-After."""

    def __init__(
        self,
        route_class: str,
        reason: str,
        retry_after_s: float,
        status: int = 429,
    ):
        super().__init__(
            f"{route_class} shed ({reason}); retry after {retry_after_s:.1f}s"
        )
        self.route_class = route_class
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.status = status


class TokenBucket:
    """Classic token bucket: `burst` capacity, `rate` tokens/sec refill.

    `try_acquire` returns 0.0 when a token was taken, else the seconds
    until one refills (the Retry-After hint)."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        if rate <= 0:
            raise ValueError("token bucket rate must be positive")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()
        self._lock = threading.Lock()

    def try_acquire(self) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self.rate


@dataclass
class AdmissionConfig:
    """Knobs (mirrored by the aggregator Config / YAML; docs/INGEST.md)."""

    # requests/sec sustained + burst per route class; rate 0 = unlimited
    upload_bucket_rate: float = 0.0
    upload_bucket_burst: int = 0
    aggregate_bucket_rate: float = 0.0
    aggregate_bucket_burst: int = 0
    # first entry sheds first as pipeline occupancy rises
    shed_priority: tuple[str, ...] = ("upload", "aggregate")
    # occupancy fraction at which the first priority class sheds
    queue_high_watermark: float = 0.75
    # Retry-After for queue-pressure sheds (bucket sheds compute the
    # exact refill time instead)
    shed_retry_after_s: float = 1.0


class AdmissionController:
    """Evaluates both admission signals for one route class.

    `depth_fn() -> (in_flight, bound)` reports the ingest pipeline's
    occupancy; the controller derives per-class watermarks from the
    configured shed priority."""

    def __init__(self, cfg: AdmissionConfig, depth_fn=None, supervisor_fn=None):
        self.cfg = cfg
        self._depth_fn = depth_fn
        # optional datastore supervisor accessor (degraded-mode serving,
        # docs/ROBUSTNESS.md): while the datastore is not up, the
        # aggregate-step routes — whose handlers go straight into
        # datastore transactions — shed 503 up front, while client
        # uploads keep flowing (they land in the durable spill journal)
        self._supervisor_fn = supervisor_fn or (lambda: None)
        self._buckets: dict[str, TokenBucket] = {}
        if cfg.upload_bucket_rate > 0:
            self._buckets["upload"] = TokenBucket(
                cfg.upload_bucket_rate, cfg.upload_bucket_burst or cfg.upload_bucket_rate
            )
        if cfg.aggregate_bucket_rate > 0:
            self._buckets["aggregate"] = TokenBucket(
                cfg.aggregate_bucket_rate,
                cfg.aggregate_bucket_burst or cfg.aggregate_bucket_rate,
            )
        # watermarks spaced across [high_watermark, 1.0) in shed order:
        # with the default priority and high=0.75, uploads shed at 75%
        # occupancy and aggregate steps at 87.5%
        n = max(1, len(cfg.shed_priority))
        hw = min(max(cfg.queue_high_watermark, 0.0), 1.0)
        self._watermarks = {
            cls: hw + (1.0 - hw) * i / n for i, cls in enumerate(cfg.shed_priority)
        }

    def watermark(self, route_class: str) -> float | None:
        return self._watermarks.get(route_class)

    def admit(self, route_class: str, deadline: float | None = None) -> None:
        """Raise ShedError if this request must be refused.

        `deadline`: the caller's propagated budget as an absolute
        time.monotonic() value (core.deadline.parse_header — already
        backdated by the time the request sat in the accept queue).
        Work whose budget died in transit or while queued is shed 503
        BEFORE any HPKE/datastore cost: the leader has already stepped
        back (or will, on this 503's heels within its own budget), so
        every cycle spent on it would be pure amplification."""
        if deadline is not None and time.monotonic() >= deadline:
            raise ShedError(
                route_class,
                "deadline_expired",
                self.cfg.shed_retry_after_s,
                status=503,
            )
        if route_class == "aggregate":
            supervisor = self._supervisor_fn()
            if supervisor is not None and supervisor.state != "up":
                raise ShedError(
                    route_class,
                    f"datastore_{supervisor.state}",
                    supervisor.reconnect_delay_s(),
                    status=503,
                )
        wm = self._watermarks.get(route_class)
        if wm is not None and self._depth_fn is not None:
            depth, bound = self._depth_fn()
            if bound > 0 and depth >= wm * bound:
                raise ShedError(route_class, "queue", self.cfg.shed_retry_after_s)
        bucket = self._buckets.get(route_class)
        if bucket is not None:
            wait = bucket.try_acquire()
            if wait > 0:
                # never advertise a zero-second retry: a refill window
                # shorter than the clock tick still needs a 1s nudge
                raise ShedError(route_class, "rate", max(wait, 1.0))
