"""Staged, bounded upload ingest pipeline with window-batched crypto.

Replaces the per-handler-thread upload path (decode + HPKE open +
validate + write, all on the request thread) with fixed-size stages
connected by bounded queues:

    handler thread ──submit──▶ [decode q] ─▶ decode worker(s)
        (drains a flush WINDOW of raw bodies, parses them columnar via
         decode_reports_fast, runs the cheap time/keypair checks per
         lane — one malformed upload rejects its own lane, never its
         window)
                              ─▶ [decrypt q] ─▶ decrypt pool
        (whole windows: lanes grouped by (task, HPKE config) run ONE
         hpke_open_batch — shared EVP objects, one-shot HKDF, one
         reused cipher context — and one numpy range-validation pass.
         Whether the batch call parallelizes across workers is a
         backend property: the `cryptography` wheel releases the GIL,
         the ctypes-libcrypto fallback holds it for the window (PyDLL
         convoy note in core/hpke_backend.py) — the default pool size
         comes from that capability, see default_decrypt_workers)
                              ─▶ ReportWriteBatcher group commit
        (one datastore transaction per accumulated batch; the batch's
         flush resolves every ticket it carried)

The handler thread parks on an `UploadTicket` until its report's batch
commits, so HTTP semantics are unchanged (201 after durable write,
replays still 201, stage errors map to the same problem documents).
Capacity behavior is also unchanged from the pre-batching pipeline:
in-flight uploads are bounded by `queue_depth`, the bound sheds
ShedError (429 + Retry-After at the HTTP layer), and per-report
admission/problem-document mapping is preserved lane-by-lane.

`batch_window` bounds how many uploads one decode pass drains;
`batch_linger_ms` is how long a decode worker waits for the window to
fill once it holds at least one upload (group-commit style: drain
whatever is queued, linger briefly for stragglers). `batch_window: 1`
restores the exact per-report path (the verification oracle), which is
also what lanes fall back to when a TaskAggregator double doesn't
implement the batch surface.

Stage occupancy is exported as `janus_ingest_queue_depth{stage=…}` /
`janus_ingest_inflight` gauges, per-report stage latency as
`janus_ingest_stage_duration_seconds{stage=…}` (batched windows
observe the window's amortized per-report share), achieved batch sizes
as `janus_hpke_batch_size`, whole-window decrypt wall time as
`janus_ingest_decrypt_batch_seconds`, and each report's stages emit
`ingest.decode` / `ingest.decrypt` spans parented under the
originating request's `dap.upload` span (trace context rides the
ticket across threads; batched spans carry a `batch=` attribute).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

from .. import failpoints, metrics, trace
from ..messages import Report, decode_reports_fast
from .admission import ShedError

log = logging.getLogger(__name__)

_STOP = object()


def default_decrypt_workers(batched: bool = True) -> int:
    """Decrypt-pool size when the config leaves it 0.

    With a backend whose batch HPKE-open releases the GIL (the
    `cryptography` wheel), the pool scales with cores: one worker per
    host core, floor 2. On the ctypes-libcrypto fallback a batched
    open HOLDS the GIL for its whole window (PyDLL — see
    core/hpke_backend.py), so crypto from N workers serializes anyway
    and extra workers only add convoy switches; 2 workers is the
    measured crossover on this host — the second overlaps the numpy
    validation (which releases the GIL) and the commit bookkeeping
    with the next window's GIL-held crypto (docs/INGEST.md "Sizing the
    decrypt pool")."""
    from ..core import hpke_backend

    cores = max(2, os.cpu_count() or 2)
    if batched and not hpke_backend.BATCH_RELEASES_GIL:
        return min(2, cores)
    return cores


class UploadTicket:
    """One admitted upload's journey through the pipeline."""

    __slots__ = (
        "ta",
        "clock",
        "body",
        "report",
        "keypair",
        "trace_ctx",
        "event",
        "fresh",
        "error",
        "t_submit",
    )

    def __init__(self, ta, clock, body: bytes):
        self.ta = ta
        self.clock = clock
        self.body = body
        self.report = None
        self.keypair = None
        self.trace_ctx = trace.current_context()
        self.event = threading.Event()
        self.fresh: bool | None = None
        self.error: BaseException | None = None
        self.t_submit = time.monotonic()

    def result(self, timeout_s: float = 30.0) -> bool:
        """Block until committed; returns False on replay, raises the
        stage error otherwise (the handler maps it to a problem doc)."""
        if not self.event.wait(timeout_s):
            raise TimeoutError("upload did not commit in time")
        if self.error is not None:
            raise self.error
        assert self.fresh is not None
        return self.fresh


class _DecryptWindow:
    """One decoded window headed for the decrypt stage: the shared
    ReportColumn plus the surviving (ticket, lane index) pairs."""

    __slots__ = ("col", "lanes")

    def __init__(self, col, lanes):
        self.col = col
        self.lanes = lanes  # list[(UploadTicket, int)]


class IngestPipeline:
    """Bounded staged ingest; see module docstring.

    `writer` is the aggregator's ReportWriteBatcher (group commit).
    Threads start lazily on first submit and are daemons; `close()`
    drains them for orderly shutdown."""

    def __init__(
        self,
        writer,
        decrypt_workers: int = 0,
        decode_workers: int = 1,
        # default matches aggregator Config.ingest_queue_depth; must
        # stay below the HTTP handler-pool bound to be reachable
        queue_depth: int = 24,
        # flush-window batching (ISSUE 11): how many uploads one decode
        # pass may drain into a single columnar decode + batched
        # decrypt, and how long to linger for the window to fill once
        # at least one upload is held. window 1 = per-report oracle.
        batch_window: int = 32,
        batch_linger_ms: float = 2.0,
    ):
        self.writer = writer
        self.batch_window = max(1, batch_window)
        self.batch_linger_s = max(0.0, batch_linger_ms) / 1000.0
        self.decrypt_workers = decrypt_workers or default_decrypt_workers(
            self.batch_window > 1
        )
        self.decode_workers = max(1, decode_workers)
        self.queue_depth = max(1, queue_depth)
        # queues sized to the in-flight bound so intra-pipeline puts
        # never block; the bound itself is enforced on _inflight
        self._decode_q: queue.Queue = queue.Queue(maxsize=self.queue_depth)
        self._decrypt_q: queue.Queue = queue.Queue(maxsize=self.queue_depth)
        self._inflight = 0
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._started = False
        self._stop = False

    # ------------------------------------------------------------------
    # occupancy (the admission controller's queue-depth signal)
    # ------------------------------------------------------------------
    def depth(self) -> tuple[int, int]:
        """(uploads in flight, configured bound)."""
        return self._inflight, self.queue_depth

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, ta, clock, body: bytes) -> UploadTicket:
        """Admit one raw upload body. Raises ShedError when the
        in-flight bound is hit (the queue-full backstop behind the
        admission controller's watermark)."""
        ticket = UploadTicket(ta, clock, body)
        with self._lock:
            if self._stop:
                raise RuntimeError("ingest pipeline is closed")
            if self._inflight >= self.queue_depth:
                raise ShedError("upload", "queue_full", 1.0)
            self._inflight += 1
            metrics.ingest_inflight.set(self._inflight)
            if not self._started:
                self._start_locked()
            # enqueue under the lock (never blocks: queue capacity ==
            # the in-flight bound) so close() — which flips _stop under
            # this lock before inserting its stop sentinels — can't
            # interleave here and strand a ticket behind a sentinel
            self._decode_q.put(ticket)
        metrics.ingest_queue_depth.set(self._decode_q.qsize(), stage="decode")
        return ticket

    def _start_locked(self) -> None:
        decode_target = (
            self._decode_loop if self.batch_window > 1 else self._decode_loop_single
        )
        decrypt_target = (
            self._decrypt_loop if self.batch_window > 1 else self._decrypt_loop_single
        )
        for i in range(self.decode_workers):
            t = threading.Thread(
                target=decode_target, name=f"ingest-decode-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        for i in range(self.decrypt_workers):
            t = threading.Thread(
                target=decrypt_target, name=f"ingest-decrypt-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        self._started = True

    # ------------------------------------------------------------------
    # shared stage plumbing
    # ------------------------------------------------------------------
    def _resolve(self, ticket: UploadTicket, fresh=None, error=None) -> None:
        ticket.fresh = fresh
        ticket.error = error
        with self._lock:
            self._inflight -= 1
            metrics.ingest_inflight.set(self._inflight)
        ticket.event.set()

    def _submit_stored(self, ticket: UploadTicket, stored) -> None:
        """Hand one validated report to the group-commit writer; the
        flusher thread resolves the ticket when its batch lands."""
        t_commit = time.monotonic()

        def on_done(pending, ticket=ticket, t_commit=t_commit):
            # flusher thread: the group commit carrying this report
            # finished (fresh/replay) or failed
            wait_s = time.monotonic() - t_commit
            metrics.ingest_stage_duration.observe(wait_s, stage="commit")
            # marker span in the upload's trace: its position shows
            # WHEN the group commit landed relative to decrypt, and
            # its wait_s attribute carries the queue-to-durable gap
            # (the flight recorder keeps it even with no writer)
            with trace.use_context(ticket.trace_ctx), trace.span(
                "ingest.commit", wait_s=round(wait_s, 6)
            ):
                pass
            if pending.error is not None:
                self._resolve(ticket, error=pending.error)
            else:
                self._resolve(ticket, fresh=pending.fresh)

        try:
            self.writer.submit_report(stored, on_done=on_done)
        except BaseException as e:
            self._resolve(ticket, error=e)

    # ------------------------------------------------------------------
    # batched stages (the serving path; ISSUE 11)
    # ------------------------------------------------------------------
    def _drain_window(self, first: UploadTicket):
        """Collect up to batch_window tickets: whatever is already
        queued, lingering batch_linger_s for stragglers. A _STOP
        drained mid-window is honored AFTER the window (returned as
        stop=True so the worker processes what it holds, then exits —
        close() inserts one sentinel per worker)."""
        window = [first]
        deadline = time.monotonic() + self.batch_linger_s
        while len(window) < self.batch_window:
            timeout = deadline - time.monotonic()
            try:
                if timeout > 0:
                    t = self._decode_q.get(timeout=timeout)
                else:
                    t = self._decode_q.get_nowait()
            except queue.Empty:
                break
            if t is _STOP:
                return window, True
            window.append(t)
        return window, False

    def _decode_loop(self) -> None:
        while True:
            first = self._decode_q.get()
            if first is _STOP:
                return
            window, stop = self._drain_window(first)
            metrics.ingest_queue_depth.set(self._decode_q.qsize(), stage="decode")
            try:
                self._decode_window(window)
            except BaseException:  # never kill the worker; fail the window
                log.exception("ingest decode window failed")
                for t in window:
                    if not t.event.is_set():
                        self._resolve(
                            t, error=RuntimeError("ingest decode stage failed")
                        )
            if stop:
                return

    def _decode_window(self, window: list) -> None:
        t0 = time.monotonic()
        col = decode_reports_fast([t.body for t in window])
        for t in window:
            t.body = b""  # decoded; free the raw copy

        # pass 1 per lane: failpoint + parse verdict, inside the lane's
        # own trace context (failpoint BEFORE the decode error, exactly
        # like the per-report path: an armed ingest.decode failpoint
        # wins over a malformed body)
        survivors: list[tuple[UploadTicket, int]] = []
        by_ta: dict[int, list[tuple[UploadTicket, int]]] = {}
        for i, ticket in enumerate(window):
            try:
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.decode", batch=len(window)
                ):
                    failpoints.hit("ingest.decode")
                    err = col.errors[i]
                    if err is not None:
                        raise err
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            by_ta.setdefault(id(ticket.ta), []).append((ticket, i))

        # pass 2 per task group: the cheap admission checks. Tasks with
        # the batch surface run them columnar; doubles without it fall
        # back to the per-report oracle on a realized Report.
        for lanes in by_ta.values():
            ta = lanes[0][0].ta
            prepare_cols = getattr(ta, "upload_prepare_columns", None)
            if prepare_cols is not None:
                results = prepare_cols(lanes[0][0].clock, col, [i for _, i in lanes])
                for (ticket, i), res in zip(lanes, results):
                    if isinstance(res, BaseException):
                        self._resolve(ticket, error=res)
                    else:
                        ticket.keypair = res
                        survivors.append((ticket, i))
            else:
                for ticket, i in lanes:
                    try:
                        ticket.report = col.report(i)
                        ticket.keypair = ticket.ta.upload_prepare(
                            ticket.clock, ticket.report
                        )
                    except BaseException as e:
                        self._resolve(ticket, error=e)
                        continue
                    survivors.append((ticket, i))

        dt = time.monotonic() - t0
        per_report = dt / max(1, len(window))
        for _ in window:
            metrics.ingest_stage_duration.observe(per_report, stage="decode")
        if not survivors:
            return
        self._decrypt_q.put(_DecryptWindow(col, survivors))
        metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")

    def _decrypt_loop(self) -> None:
        while True:
            item = self._decrypt_q.get()
            if item is _STOP:
                return
            metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")
            try:
                self._decrypt_window(item)
            except BaseException:
                log.exception("ingest decrypt window failed")
                for ticket, _ in item.lanes:
                    if not ticket.event.is_set():
                        self._resolve(
                            ticket, error=RuntimeError("ingest decrypt stage failed")
                        )

    def _decrypt_window(self, item: _DecryptWindow) -> None:
        t0 = time.monotonic()
        col = item.col
        # per-lane failpoint first (budget semantics match the
        # per-report path: a fired lane rejects without crypto)
        live: list[tuple[UploadTicket, int]] = []
        for ticket, i in item.lanes:
            try:
                with trace.use_context(ticket.trace_ctx):
                    failpoints.hit("ingest.decrypt")
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            live.append((ticket, i))

        # group by (task, HPKE config id): one batched open per group.
        # The config id comes from the decoded column, not keypair
        # object identity — equal configs resolved through different
        # lookups must still share a batch.
        groups: dict[tuple, list[tuple[UploadTicket, int]]] = {}
        for ticket, i in live:
            groups.setdefault(
                (id(ticket.ta), col.leader_config_ids[i]), []
            ).append((ticket, i))

        for lanes in groups.values():
            ta = lanes[0][0].ta
            keypair = lanes[0][0].keypair
            batch = getattr(ta, "upload_decrypt_validate_batch", None)
            if batch is not None:
                with trace.span("ingest.decrypt_batch", batch=len(lanes)):
                    results = batch(col, [i for _, i in lanes], keypair)
                for (ticket, i), res in zip(lanes, results):
                    with trace.use_context(ticket.trace_ctx), trace.span(
                        "ingest.decrypt", batch=len(lanes)
                    ):
                        pass  # marker: this lane's decrypt ran in the batch
                    if isinstance(res, BaseException):
                        self._resolve(ticket, error=res)
                    else:
                        self._submit_stored(ticket, res)
            else:
                # oracle fallback for doubles without the batch surface
                for ticket, i in lanes:
                    try:
                        with trace.use_context(ticket.trace_ctx), trace.span(
                            "ingest.decrypt"
                        ):
                            report = ticket.report or col.report(i)
                            stored = ticket.ta.upload_decrypt_validate(
                                report, ticket.keypair
                            )
                    except BaseException as e:
                        self._resolve(ticket, error=e)
                        continue
                    self._submit_stored(ticket, stored)

        dt = time.monotonic() - t0
        metrics.ingest_decrypt_batch_seconds.observe(dt)
        per_report = dt / max(1, len(item.lanes))
        for _ in item.lanes:
            metrics.ingest_stage_duration.observe(per_report, stage="decrypt")

    # ------------------------------------------------------------------
    # single-report stages (batch_window=1: the pre-batching path,
    # kept verbatim as the verification oracle and fallback mode)
    # ------------------------------------------------------------------
    def _decode_loop_single(self) -> None:
        while True:
            ticket = self._decode_q.get()
            if ticket is _STOP:
                return
            metrics.ingest_queue_depth.set(self._decode_q.qsize(), stage="decode")
            t0 = time.monotonic()
            try:
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.decode"
                ):
                    failpoints.hit("ingest.decode")
                    ticket.report = Report.from_bytes(ticket.body)
                    ticket.body = b""  # decoded; free the raw copy
                    ticket.keypair = ticket.ta.upload_prepare(
                        ticket.clock, ticket.report
                    )
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            finally:
                metrics.ingest_stage_duration.observe(
                    time.monotonic() - t0, stage="decode"
                )
            self._decrypt_q.put(ticket)
            metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")

    def _decrypt_loop_single(self) -> None:
        while True:
            ticket = self._decrypt_q.get()
            if ticket is _STOP:
                return
            metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")
            t0 = time.monotonic()
            try:
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.decrypt"
                ):
                    failpoints.hit("ingest.decrypt")
                    stored = ticket.ta.upload_decrypt_validate(
                        ticket.report, ticket.keypair
                    )
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            finally:
                metrics.ingest_stage_duration.observe(
                    time.monotonic() - t0, stage="decrypt"
                )
            self._submit_stored(ticket, stored)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._stop = True
            started = self._started
        if not started:
            return
        for _ in range(self.decode_workers):
            self._decode_q.put(_STOP)
        for _ in range(self.decrypt_workers):
            self._decrypt_q.put(_STOP)
        for t in self._threads:
            t.join(timeout=5)
        # fail any ticket a worker handed forward after its peers took
        # the stop sentinels (decode can enqueue behind a decrypt
        # sentinel): nothing will consume it, and its handler thread
        # must get an immediate error, not a 30s result() timeout
        for q in (self._decode_q, self._decrypt_q):
            while True:
                try:
                    t = q.get_nowait()
                except queue.Empty:
                    break
                if t is _STOP:
                    continue
                if isinstance(t, _DecryptWindow):
                    for ticket, _ in t.lanes:
                        if not ticket.event.is_set():
                            self._resolve(
                                ticket,
                                error=RuntimeError("ingest pipeline is closed"),
                            )
                else:
                    self._resolve(
                        t, error=RuntimeError("ingest pipeline is closed")
                    )
