"""Staged, bounded upload ingest pipeline.

Replaces the per-handler-thread upload path (decode + HPKE open +
validate + write, all on the request thread) with fixed-size stages
connected by bounded queues:

    handler thread ──submit──▶ [decode q] ─▶ decode worker(s)
        (parse Report, cheap time/keypair checks)
                              ─▶ [decrypt q] ─▶ decrypt pool (≈ host cores)
        (HPKE open + columnar share validation — the CPU-heavy stage.
         What actually runs in parallel is the numpy share validation,
         which releases the GIL; the HPKE open itself holds the GIL on
         the ctypes-libcrypto fallback — deliberately, see the PyDLL
         note in core/hpke_backend.py — and releases it only with the
         `cryptography` wheel installed)
                              ─▶ ReportWriteBatcher group commit
        (one datastore transaction per accumulated batch; the batch's
         flush resolves every ticket it carried)

The handler thread parks on an `UploadTicket` until its report's batch
commits, so HTTP semantics are unchanged (201 after durable write,
replays still 201). What changes is capacity behavior: in-flight
uploads are bounded by `queue_depth`; when the bound is hit `submit`
raises ShedError (429 + Retry-After at the HTTP layer) instead of
growing threads; and decryption throughput scales with the worker pool
rather than with the (unbounded) number of connections.

Stage occupancy is exported as `janus_ingest_queue_depth{stage=…}` /
`janus_ingest_inflight` gauges, per-report stage latency as
`janus_ingest_stage_duration_seconds{stage=…}`, and each stage runs in
an `ingest.decode` / `ingest.decrypt` span parented under the
originating request's `dap.upload` span (trace context rides the
ticket across threads).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

from .. import failpoints, metrics, trace
from ..messages import Report
from .admission import ShedError

log = logging.getLogger(__name__)

_STOP = object()


def default_decrypt_workers() -> int:
    """One per host core, floor 2 (the decrypt stage is the CPU-heavy
    one; cores beyond the queue bound buy nothing)."""
    return max(2, os.cpu_count() or 2)


class UploadTicket:
    """One admitted upload's journey through the pipeline."""

    __slots__ = (
        "ta",
        "clock",
        "body",
        "report",
        "keypair",
        "trace_ctx",
        "event",
        "fresh",
        "error",
        "t_submit",
    )

    def __init__(self, ta, clock, body: bytes):
        self.ta = ta
        self.clock = clock
        self.body = body
        self.report = None
        self.keypair = None
        self.trace_ctx = trace.current_context()
        self.event = threading.Event()
        self.fresh: bool | None = None
        self.error: BaseException | None = None
        self.t_submit = time.monotonic()

    def result(self, timeout_s: float = 30.0) -> bool:
        """Block until committed; returns False on replay, raises the
        stage error otherwise (the handler maps it to a problem doc)."""
        if not self.event.wait(timeout_s):
            raise TimeoutError("upload did not commit in time")
        if self.error is not None:
            raise self.error
        assert self.fresh is not None
        return self.fresh


class IngestPipeline:
    """Bounded staged ingest; see module docstring.

    `writer` is the aggregator's ReportWriteBatcher (group commit).
    Threads start lazily on first submit and are daemons; `close()`
    drains them for orderly shutdown."""

    def __init__(
        self,
        writer,
        decrypt_workers: int = 0,
        decode_workers: int = 1,
        # default matches aggregator Config.ingest_queue_depth; must
        # stay below the HTTP handler-pool bound to be reachable
        queue_depth: int = 24,
    ):
        self.writer = writer
        self.decrypt_workers = decrypt_workers or default_decrypt_workers()
        self.decode_workers = max(1, decode_workers)
        self.queue_depth = max(1, queue_depth)
        # queues sized to the in-flight bound so intra-pipeline puts
        # never block; the bound itself is enforced on _inflight
        self._decode_q: queue.Queue = queue.Queue(maxsize=self.queue_depth)
        self._decrypt_q: queue.Queue = queue.Queue(maxsize=self.queue_depth)
        self._inflight = 0
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._started = False
        self._stop = False

    # ------------------------------------------------------------------
    # occupancy (the admission controller's queue-depth signal)
    # ------------------------------------------------------------------
    def depth(self) -> tuple[int, int]:
        """(uploads in flight, configured bound)."""
        return self._inflight, self.queue_depth

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, ta, clock, body: bytes) -> UploadTicket:
        """Admit one raw upload body. Raises ShedError when the
        in-flight bound is hit (the queue-full backstop behind the
        admission controller's watermark)."""
        ticket = UploadTicket(ta, clock, body)
        with self._lock:
            if self._stop:
                raise RuntimeError("ingest pipeline is closed")
            if self._inflight >= self.queue_depth:
                raise ShedError("upload", "queue_full", 1.0)
            self._inflight += 1
            metrics.ingest_inflight.set(self._inflight)
            if not self._started:
                self._start_locked()
            # enqueue under the lock (never blocks: queue capacity ==
            # the in-flight bound) so close() — which flips _stop under
            # this lock before inserting its stop sentinels — can't
            # interleave here and strand a ticket behind a sentinel
            self._decode_q.put(ticket)
        metrics.ingest_queue_depth.set(self._decode_q.qsize(), stage="decode")
        return ticket

    def _start_locked(self) -> None:
        for i in range(self.decode_workers):
            t = threading.Thread(
                target=self._decode_loop, name=f"ingest-decode-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        for i in range(self.decrypt_workers):
            t = threading.Thread(
                target=self._decrypt_loop, name=f"ingest-decrypt-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        self._started = True

    # ------------------------------------------------------------------
    # stages
    # ------------------------------------------------------------------
    def _resolve(self, ticket: UploadTicket, fresh=None, error=None) -> None:
        ticket.fresh = fresh
        ticket.error = error
        with self._lock:
            self._inflight -= 1
            metrics.ingest_inflight.set(self._inflight)
        ticket.event.set()

    def _decode_loop(self) -> None:
        while True:
            ticket = self._decode_q.get()
            if ticket is _STOP:
                return
            metrics.ingest_queue_depth.set(self._decode_q.qsize(), stage="decode")
            t0 = time.monotonic()
            try:
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.decode"
                ):
                    failpoints.hit("ingest.decode")
                    ticket.report = Report.from_bytes(ticket.body)
                    ticket.body = b""  # decoded; free the raw copy
                    ticket.keypair = ticket.ta.upload_prepare(
                        ticket.clock, ticket.report
                    )
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            finally:
                metrics.ingest_stage_duration.observe(
                    time.monotonic() - t0, stage="decode"
                )
            self._decrypt_q.put(ticket)
            metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")

    def _decrypt_loop(self) -> None:
        while True:
            ticket = self._decrypt_q.get()
            if ticket is _STOP:
                return
            metrics.ingest_queue_depth.set(self._decrypt_q.qsize(), stage="decrypt")
            t0 = time.monotonic()
            try:
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.decrypt"
                ):
                    failpoints.hit("ingest.decrypt")
                    stored = ticket.ta.upload_decrypt_validate(
                        ticket.report, ticket.keypair
                    )
            except BaseException as e:
                self._resolve(ticket, error=e)
                continue
            finally:
                metrics.ingest_stage_duration.observe(
                    time.monotonic() - t0, stage="decrypt"
                )
            t_commit = time.monotonic()

            def on_done(pending, ticket=ticket, t_commit=t_commit):
                # flusher thread: the group commit carrying this report
                # finished (fresh/replay) or failed
                wait_s = time.monotonic() - t_commit
                metrics.ingest_stage_duration.observe(wait_s, stage="commit")
                # marker span in the upload's trace: its position shows
                # WHEN the group commit landed relative to decrypt, and
                # its wait_s attribute carries the queue-to-durable gap
                # (the flight recorder keeps it even with no writer)
                with trace.use_context(ticket.trace_ctx), trace.span(
                    "ingest.commit", wait_s=round(wait_s, 6)
                ):
                    pass
                if pending.error is not None:
                    self._resolve(ticket, error=pending.error)
                else:
                    self._resolve(ticket, fresh=pending.fresh)

            try:
                self.writer.submit_report(stored, on_done=on_done)
            except BaseException as e:
                self._resolve(ticket, error=e)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._stop = True
            started = self._started
        if not started:
            return
        for _ in range(self.decode_workers):
            self._decode_q.put(_STOP)
        for _ in range(self.decrypt_workers):
            self._decrypt_q.put(_STOP)
        for t in self._threads:
            t.join(timeout=5)
        # fail any ticket a worker handed forward after its peers took
        # the stop sentinels (decode can enqueue behind a decrypt
        # sentinel): nothing will consume it, and its handler thread
        # must get an immediate error, not a 30s result() timeout
        for q in (self._decode_q, self._decrypt_q):
            while True:
                try:
                    t = q.get_nowait()
                except queue.Empty:
                    break
                if t is not _STOP:
                    self._resolve(
                        t, error=RuntimeError("ingest pipeline is closed")
                    )
