"""Durable upload spill journal: datastore-outage survival for the
DAP upload path (docs/ROBUSTNESS.md "Datastore outages").

The DAP ack contract is `201 ⇒ eventually aggregated exactly once`,
and the only thing a 201 may rest on is a durable write. When the
datastore is unreachable (connection-class failure) or drowning
(commit latency past the spill threshold), the ReportWriteBatcher
appends the already-validated report rows HERE — a CRC-framed,
segmented, fsync-on-ack append-only journal on local disk — and the
upload is acked on the strength of that fsync. A background
JournalReplayer drains segments back through the write batcher once
the datastore recovers; the datastore's report-id primary key makes
replay idempotent (duplicate ⇒ replayed-ok), and a segment is
truncated only after the transaction covering every row in it has
committed.

Durability/ordering contract:

  * **fsync-on-ack**: `append_batch` returns only after the frames and
    the fsync land; a 201 resting on the journal survives process
    death and OS crash (modulo disk loss — the journal is a
    *same-host* durability story, like a WAL).
  * **Idempotent replay**: rows are replayed through the same
    `put_client_report` ON CONFLICT DO NOTHING path as live uploads;
    a crash between replay-commit and truncate re-replays the segment
    harmlessly (every row dedups).
  * **Truncate after commit**: a segment is unlinked only after
    `flush_direct` returned for every row in it, so no acked report
    can exist solely in an unlinked file.
  * **Torn tails tolerated, damage quarantined**: a crash/ENOSPC
    mid-append leaves a TRUNCATED final frame (sequential writes always
    end short) — those rows were never acked (the fsync hadn't
    returned) and the valid prefix replays + truncates normally. A
    complete frame failing its CRC is genuine damage: the prefix still
    replays, but the file is QUARANTINED on disk as `.corrupt` (ERROR
    log + statusz count) because frames past the damage may hold acked
    data — never silently truncated, never a boot crash-loop.
  * **Bounded**: `max_total_bytes` / `max_segments` cap the journal;
    a full journal sheds uploads with `503 + Retry-After`
    (JournalFull) — bounded lies beat unbounded truth-on-disk.
  * **Encrypted at rest**: the leader input share is encrypted with
    the datastore Crypter (AAD table "upload_journal") under the same
    key rotation as the database, so spilled plaintext shares never
    touch disk.

Frame format (little-endian):

    "JUJ1" | u32 payload_len | u32 crc32(len_le || payload) | payload

(the CRC covers the length so a flipped length field reads as damage,
not as a benign torn tail; the magic lets the reader tell "file ends
here" from "damage with more frames behind it"). Payload: task_id(32)
report_id(16) client_time(u64) then length-prefixed public_share,
encrypted leader_input_share and helper_encrypted_input_share.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib

from .admission import ShedError

log = logging.getLogger(__name__)

# frame = magic | u32 payload_len | u32 crc32(len_le || payload) | payload
# — the CRC covers the LENGTH so a bit-flipped length field cannot
# masquerade as a benign truncated tail, and the magic lets the reader
# tell "file ends here" (torn tail) from "damage with more frames
# behind it" (quarantine, never truncate)
_FRAME_MAGIC = b"JUJ1"
_FRAME_HDR = struct.Struct("<II")
_SEGMENT_PREFIX = "upload-journal-"
_SEGMENT_SUFFIX = ".wal"
_QUARANTINE_SUFFIX = ".corrupt"


def _frame(payload: bytes) -> bytes:
    len_le = struct.pack("<I", len(payload))
    crc = zlib.crc32(len_le + payload) & 0xFFFFFFFF
    return _FRAME_MAGIC + len_le + struct.pack("<I", crc) + payload


class JournalFull(ShedError):
    """The bounded journal cannot absorb more spilled uploads: shed
    with 503 + Retry-After (the datastore is down AND the local buffer
    is exhausted — the honest answer is 'come back later')."""

    def __init__(self, retry_after_s: float = 30.0):
        super().__init__("upload", "journal_full", retry_after_s)
        self.status = 503


def _encode_row(crypter, report) -> bytes:
    """LeaderStoredReport -> frame payload (share encrypted at rest)."""
    row_key = report.task_id.data + report.report_id.data
    enc_share = crypter.encrypt(
        "upload_journal", row_key, "leader_input_share", report.leader_input_share
    )
    helper = report.helper_encrypted_input_share.to_bytes()
    public = report.public_share or b""
    return b"".join(
        (
            report.task_id.data,
            report.report_id.data,
            struct.pack("<Q", report.client_time.seconds),
            struct.pack("<I", len(public)),
            public,
            struct.pack("<I", len(enc_share)),
            enc_share,
            struct.pack("<I", len(helper)),
            helper,
        )
    )


def _decode_row(crypter, payload: bytes):
    from ..datastore.models import LeaderStoredReport
    from ..messages import HpkeCiphertext, ReportId, TaskId, Time

    off = 0

    def take(n: int) -> bytes:
        nonlocal off
        if off + n > len(payload):
            raise ValueError("journal row truncated")
        out = payload[off : off + n]
        off += n
        return out

    task_id = take(32)
    report_id = take(16)
    (client_time,) = struct.unpack("<Q", take(8))
    (n,) = struct.unpack("<I", take(4))
    public = take(n)
    (n,) = struct.unpack("<I", take(4))
    enc_share = take(n)
    (n,) = struct.unpack("<I", take(4))
    helper = take(n)
    share = crypter.decrypt(
        "upload_journal", task_id + report_id, "leader_input_share", enc_share
    )
    return LeaderStoredReport(
        TaskId(task_id),
        ReportId(report_id),
        Time(client_time),
        public,
        share,
        HpkeCiphertext.from_bytes(helper),
    )


def _read_frames(path: str) -> tuple[list[bytes], str]:
    """(payloads, reason) where reason is:

      "clean"      every frame decoded
      "truncated"  the file ends inside the LAST frame — the signature
                   of a crash/ENOSPC mid-append; the missing rows were
                   never acked, so the prefix is safe to replay AND the
                   segment safe to truncate after it lands
      "crc"        damage with (possibly) acked frames behind it — a
                   checksum/magic failure, or an undecodable region
                   followed by another frame magic; the prefix is
                   replayed but the file must be QUARANTINED
                   (preserved on disk), never truncated

    Always stops at the first invalid frame. The "is there another
    frame magic after the damage?" scan is what keeps a corrupted
    length field from masquerading as a benign torn tail."""
    payloads: list[bytes] = []
    with open(path, "rb") as f:
        data = f.read()
    hdr = len(_FRAME_MAGIC) + _FRAME_HDR.size
    off = 0

    def _tail_reason(stop: int) -> str:
        # damage at `stop`: torn tail if nothing frame-like follows,
        # corruption (quarantine — the conservative direction) if a
        # later frame magic exists
        nxt = data.find(_FRAME_MAGIC, stop + 1)
        return "crc" if nxt != -1 else "truncated"

    while off < len(data):
        if off + hdr > len(data):
            return payloads, _tail_reason(off)
        if data[off : off + len(_FRAME_MAGIC)] != _FRAME_MAGIC:
            return payloads, _tail_reason(off)
        length, crc = _FRAME_HDR.unpack_from(data, off + len(_FRAME_MAGIC))
        start = off + hdr
        if start + length > len(data):
            return payloads, _tail_reason(off)
        payload = data[start : start + length]
        if zlib.crc32(struct.pack("<I", length) + payload) & 0xFFFFFFFF != crc:
            # a COMPLETE frame failing its checksum is damage even at
            # EOF (a torn sequential append leaves a short frame, not a
            # full-length one): always the quarantine direction
            return payloads, "crc"
        payloads.append(payload)
        off = start + length
    return payloads, "clean"


class UploadJournal:
    """Segmented append-only spill journal (see module docstring).

    Thread-safe; one active segment receives appends, sealed segments
    (everything older) are replay candidates. On construction the
    directory is scanned so a journal left non-empty by a crash is
    picked up by the replayer."""

    def __init__(
        self,
        directory: str,
        crypter,
        max_segment_bytes: int = 8 << 20,
        max_total_bytes: int = 256 << 20,
        max_segments: int = 1024,
        full_retry_after_s: float = 30.0,
    ):
        self.dir = os.path.abspath(os.path.expanduser(directory))
        self.crypter = crypter
        self.max_segment_bytes = max(4096, int(max_segment_bytes))
        self.max_total_bytes = max(self.max_segment_bytes, int(max_total_bytes))
        self.max_segments = max(2, int(max_segments))
        self.full_retry_after_s = float(full_retry_after_s)
        self._lock = threading.Lock()
        self._fh = None  # active segment file handle
        self._active_seq = 0
        self._active_bytes = 0
        self._active_records = 0
        # {seq: (records, bytes)} for sealed segments
        self._sealed: dict[int, tuple[int, int]] = {}
        self.fsyncs = 0
        self.appended_total = 0
        self.quarantined = 0
        # .corrupt files count toward max_total_bytes until an operator
        # removes them: quarantine preserves bytes, and a preserved
        # byte is still a byte on the bounded disk
        self.quarantined_bytes = 0
        os.makedirs(self.dir, exist_ok=True)
        self._recover()
        self._publish()

    def _fsync_dir(self, required: bool = False) -> None:
        """Persist directory entries (segment create/unlink): a file
        fsync alone does not persist its dirent. `required=True` (the
        segment-CREATE path, which acks rest on) propagates failure —
        an upload must shed rather than be acked against a dirent that
        may not survive power loss; cleanup paths stay best-effort."""
        try:
            dirfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            if required:
                raise

    # ------------------------------------------------------------------
    # accounting
    # ------------------------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{_SEGMENT_PREFIX}{seq:016d}{_SEGMENT_SUFFIX}")

    def depth(self) -> tuple[int, int, int]:
        """(records awaiting replay, bytes on disk, segment count)."""
        with self._lock:
            records = self._active_records + sum(r for r, _ in self._sealed.values())
            nbytes = self._active_bytes + sum(b for _, b in self._sealed.values())
            segments = len(self._sealed) + (1 if self._active_records else 0)
            return records, nbytes, segments

    def status(self) -> dict:
        """/statusz section."""
        records, nbytes, segments = self.depth()
        return {
            "dir": self.dir,
            "records": records,
            "bytes": nbytes,
            "segments": segments,
            "max_total_bytes": self.max_total_bytes,
            "appended_total": self.appended_total,
            "fsyncs": self.fsyncs,
            "quarantined": self.quarantined,
            "quarantined_bytes": self.quarantined_bytes,
            "full": self.is_full(),
        }

    def _publish(self) -> None:
        from .. import metrics

        records, nbytes, _ = self.depth()
        metrics.upload_journal_depth.set(float(records))
        metrics.upload_journal_bytes.set(float(nbytes))

    # a journal is reported full once less than this headroom remains:
    # readiness must flip BEFORE the next typical append is refused
    FULL_SLACK_BYTES = 4096

    def is_full(self) -> bool:
        with self._lock:
            nbytes = (
                self._active_bytes
                + sum(b for _, b in self._sealed.values())
                + self.quarantined_bytes
            )
            segments = len(self._sealed) + 1
            return (
                nbytes + self.FULL_SLACK_BYTES > self.max_total_bytes
                or segments > self.max_segments
            )

    def readiness(self) -> str | None:
        """None when the journal can absorb spills; a reason when full
        (/readyz fails — this replica can no longer honor 201s during
        an outage)."""
        if self.is_full():
            _, nbytes, segments = self.depth()
            return (
                f"upload journal full ({nbytes} bytes / {segments} segments,"
                f" cap {self.max_total_bytes} bytes / {self.max_segments} segments)"
            )
        return None

    # ------------------------------------------------------------------
    # boot recovery
    # ------------------------------------------------------------------
    def _recover(self) -> None:
        seqs = []
        quarantined_seqs = []
        for name in os.listdir(self.dir):
            if _QUARANTINE_SUFFIX in name:  # .corrupt / .corrupt.N
                # quarantined by an earlier process: still occupying
                # bounded disk until the operator deals with it — and
                # its sequence number must never be REUSED, or a later
                # quarantine's rename would overwrite the preserved file
                self.quarantined += 1
                self.quarantined_bytes += os.path.getsize(os.path.join(self.dir, name))
                stem = name.split(_QUARANTINE_SUFFIX)[0]
                if stem.startswith(_SEGMENT_PREFIX) and stem.endswith(_SEGMENT_SUFFIX):
                    try:
                        quarantined_seqs.append(
                            int(stem[len(_SEGMENT_PREFIX) : -len(_SEGMENT_SUFFIX)])
                        )
                    except ValueError:
                        pass
                continue
            if name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX):
                try:
                    seqs.append(int(name[len(_SEGMENT_PREFIX) : -len(_SEGMENT_SUFFIX)]))
                except ValueError:
                    log.warning("ignoring non-journal file %s", name)
        seqs.sort()
        for seq in seqs:
            path = self._seg_path(seq)
            # every pre-existing segment is sealed: the process that
            # wrote it is gone, and only frames whose fsync returned
            # were ever acked. A truncated tail (crash mid-append) is
            # expected and benign; a CRC-broken frame is genuine damage
            # — LOUD at boot, and the drain will replay its valid
            # prefix and then quarantine the file instead of
            # truncating it. Either way the aggregator boots.
            payloads, reason = _read_frames(path)
            if reason == "crc":
                log.error(
                    "upload journal segment %s is CORRUPT mid-segment; its "
                    "%d-record prefix will be replayed and the file "
                    "quarantined as .corrupt",
                    path,
                    len(payloads),
                )
            self._sealed[seq] = (len(payloads), os.path.getsize(path))
        self._active_seq = max(seqs + quarantined_seqs, default=0) + 1
        if self._sealed:
            log.warning(
                "upload journal recovered %d segment(s), %d record(s) awaiting replay",
                len(self._sealed),
                sum(r for r, _ in self._sealed.values()),
            )

    # ------------------------------------------------------------------
    # append (the spill path)
    # ------------------------------------------------------------------
    def _quarantine_path_locked(self, seq: int, path: str) -> None:
        self.quarantined += 1
        try:
            self.quarantined_bytes += os.path.getsize(path)
            target = path + _QUARANTINE_SUFFIX
            # never clobber an earlier quarantine's preserved bytes
            n = 1
            while os.path.exists(target):
                target = f"{path}{_QUARANTINE_SUFFIX}.{n}"
                n += 1
            os.replace(path, target)
        except OSError:
            log.exception("could not quarantine corrupt segment %s", path)
        self._fsync_dir()
        log.error(
            "upload journal segment %d is CORRUPT (acked data may be "
            "affected); quarantined as %s%s for manual recovery",
            seq,
            path,
            _QUARANTINE_SUFFIX,
        )

    def quarantine_segment(self, seq: int) -> None:
        """Move a corrupt sealed segment out of the replay queue,
        preserving its bytes as `<name>.corrupt` for manual recovery."""
        with self._lock:
            self._sealed.pop(seq, None)
            self._quarantine_path_locked(seq, self._seg_path(seq))
        self._publish()

    def _open_active_locked(self):
        if self._fh is None:
            path = self._seg_path(self._active_seq)
            created = not os.path.exists(path)
            # buffering=0: a failed buffered flush would keep the
            # unwritten remainder in the userspace buffer and emit it
            # as mid-segment garbage on the NEXT (acked) append; raw
            # writes leave nothing behind to leak
            self._fh = open(path, "ab", buffering=0)
            self._active_bytes = self._fh.tell()
            if created:
                # the dirent must be durable before any ack rests on
                # this file: a file fsync alone does not persist it
                try:
                    self._fsync_dir(required=True)
                except OSError:
                    self._fh.close()
                    self._fh = None
                    raise
        return self._fh

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        # seal by ON-DISK size, not the in-memory counters: a failed
        # first append can leave torn bytes in a file the counters say
        # is empty, and an unsealed file would sit outside the bound
        # accounting (and outside the drain's cleanup) until restart
        try:
            size = os.path.getsize(self._seg_path(self._active_seq))
        except OSError:
            size = 0
        if self._active_records or size:
            self._sealed[self._active_seq] = (self._active_records, size)
        self._active_seq += 1
        self._active_records = 0
        self._active_bytes = 0

    def append_batch(self, reports) -> None:
        """Append every report, then ONE fsync for the batch; returns
        only after the data is durable (the ack rests on it). Raises
        JournalFull when the bound is hit — callers map it to
        503 + Retry-After."""
        if not reports:
            return
        frames = [_frame(_encode_row(self.crypter, report)) for report in reports]
        nbytes = sum(len(f) for f in frames)
        with self._lock:
            total = (
                self._active_bytes
                + sum(b for _, b in self._sealed.values())
                + self.quarantined_bytes
            )
            if (
                total + nbytes > self.max_total_bytes
                or len(self._sealed) + 1 > self.max_segments
            ):
                raise JournalFull(self.full_retry_after_s)
            fh = self._open_active_locked()
            try:
                blob = b"".join(frames)
                if fh.write(blob) != len(blob):
                    raise OSError("short write to upload journal")
                os.fsync(fh.fileno())
            except BaseException:
                # ENOSPC/EIO mid-batch: roll the file back to the last
                # durable frame boundary — torn bytes left mid-file
                # would sit in FRONT of future acked frames and turn
                # them into an unreadable suffix (quarantined or
                # dropped as a "torn tail" on replay). The raw
                # (unbuffered) handle holds no leftover bytes; drop it
                # anyway so the next append starts from a clean fd.
                try:
                    os.ftruncate(fh.fileno(), self._active_bytes)
                    fh.close()
                    self._fh = None
                except OSError:
                    # cannot repair in place: abandon this segment for
                    # appends (its valid prefix stays replayable)
                    self._rotate_locked()
                raise
            self.fsyncs += 1
            self._active_bytes += nbytes
            self._active_records += len(frames)
            self.appended_total += len(frames)
            if self._active_bytes >= self.max_segment_bytes:
                self._rotate_locked()
        from .. import metrics

        metrics.upload_journal_appends_total.add(len(frames))
        self._publish()

    # ------------------------------------------------------------------
    # replay surface
    # ------------------------------------------------------------------
    def seal_active(self) -> None:
        """Make the active segment (if non-empty) available to the
        replayer; appends continue into a fresh segment."""
        with self._lock:
            if self._active_records:
                self._rotate_locked()

    def sealed_segments(self) -> list[int]:
        with self._lock:
            return sorted(self._sealed)

    def read_segment(self, seq: int) -> tuple[list, str]:
        """Decode a sealed segment's valid prefix (oldest-first) and
        report how the segment ends: "clean" / "truncated" (crash
        mid-append — never-acked tail, segment truncatable after the
        prefix lands) / "crc" (damage — segment must be QUARANTINED
        after the prefix lands, never truncated: frames past the
        damage may be acked data)."""
        path = self._seg_path(seq)
        payloads, reason = _read_frames(path)
        rows = []
        for payload in payloads:
            try:
                rows.append(_decode_row(self.crypter, payload))
            except Exception as e:
                # CRC-valid but undecodable (e.g. the crypter key was
                # rotated out): content damage — replay the decodable
                # prefix and quarantine, or the replayer would wedge on
                # this segment forever and nothing behind it would drain
                log.error(
                    "upload journal segment %s row %d undecodable (%s: %s)",
                    path,
                    len(rows),
                    type(e).__name__,
                    e,
                )
                return rows, "crc"
        if reason == "truncated":
            log.warning(
                "upload journal segment %s has a torn tail after %d record(s)",
                path,
                len(rows),
            )
        return rows, reason

    def truncate_segment(self, seq: int) -> None:
        """Remove a fully-replayed segment. ONLY call after the
        datastore transaction covering every row in it committed."""
        path = self._seg_path(seq)
        with self._lock:
            self._sealed.pop(seq, None)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        # directory fsync so the unlink itself is durable (a crash must
        # not resurrect a replayed segment... it would dedup anyway,
        # but the bound accounting should match the disk)
        self._fsync_dir()
        self._publish()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class JournalReplayer:
    """Background drain: once the datastore is reachable again, replay
    sealed segments through the ReportWriteBatcher's direct flush path
    (same transaction shape and report-id dedup as live uploads) and
    truncate each segment only after its covering commit lands.

    `supervisor_fn` returns the DatastoreSupervisor (or None): while it
    reports "down", the replayer sleeps — replaying into a dead
    database only burns the retry budget."""

    def __init__(
        self,
        journal: UploadJournal,
        writer,
        supervisor_fn=None,
        interval_s: float = 1.0,
        batch_size: int = 200,
    ):
        self.journal = journal
        self.writer = writer
        self.supervisor_fn = supervisor_fn or (lambda: None)
        self.interval_s = max(0.05, float(interval_s))
        self.batch_size = max(1, int(batch_size))
        self.replayed_fresh = 0
        self.replayed_dupes = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "JournalReplayer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="upload-journal-replay", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None

    def kick(self) -> None:
        """Wake the drain loop now (recovery notification, tests)."""
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.drain_once()
            except Exception:
                log.exception("upload journal replay pass failed; will retry")

    def drain_once(self) -> int:
        """One drain pass; returns the number of rows replayed. Safe to
        call from tests/ops tooling (manual drains go through the same
        path)."""
        records, _, _ = self.journal.depth()
        # drain on records OR leftover sealed files: a crash during the
        # very first append of an outage leaves a zero-valid-record
        # segment whose bytes would otherwise pin journal capacity
        # forever (depth counts records; the file still counts toward
        # the bound)
        if records == 0 and not self.journal.sealed_segments():
            return 0
        supervisor = self.supervisor_fn()
        if supervisor is not None and supervisor.state == "down":
            return 0
        replayed = 0
        # sealed segments first; the active one is sealed ONLY once the
        # sealed queue drained cleanly — sealing on a failing pass
        # would rotate a fresh segment every interval and exhaust
        # max_segments long before the byte bound during a long outage
        for _ in range(2):
            n, ok = self._drain_sealed()
            replayed += n
            if not ok or self._stop.is_set():
                break
            if self.journal.depth()[0] == 0:
                break
            self.journal.seal_active()
        return replayed

    def _drain_sealed(self) -> tuple[int, bool]:
        """Replay every sealed segment; (rows replayed, queue fully
        drained). A segment is removed only AFTER the transaction
        covering its whole valid prefix committed — truncated (crash
        tails are never-acked rows) for clean/torn segments,
        quarantined (bytes preserved as .corrupt) for CRC-damaged
        ones, whose post-damage region may hold acked data."""
        from .. import metrics

        replayed = 0
        for seq in self.journal.sealed_segments():
            if self._stop.is_set():
                return replayed, False
            rows, reason = self.journal.read_segment(seq)
            for lo in range(0, len(rows), self.batch_size):
                chunk = rows[lo : lo + self.batch_size]
                try:
                    outcomes = self.writer.flush_direct(chunk)
                except Exception as e:
                    # the datastore is (still) unhappy: keep the
                    # segment, retry on the next pass
                    log.warning(
                        "journal replay of segment %d failed (%s: %s); retrying later",
                        seq,
                        type(e).__name__,
                        e,
                    )
                    return replayed, False
                fresh = sum(1 for f in outcomes if f)
                dupes = len(outcomes) - fresh
                self.replayed_fresh += fresh
                self.replayed_dupes += dupes
                if fresh:
                    metrics.upload_journal_replayed_total.add(fresh, outcome="fresh")
                if dupes:
                    metrics.upload_journal_replayed_total.add(dupes, outcome="replayed")
                replayed += len(outcomes)
            # the covering commit landed: the segment may leave the queue
            if reason == "crc":
                self.journal.quarantine_segment(seq)
            else:
                self.journal.truncate_segment(seq)
                log.info("upload journal segment %d replayed and truncated", seq)
        return replayed, True
