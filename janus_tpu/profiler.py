"""Always-on continuous profiling (docs/OBSERVABILITY.md "Continuous
profiling").

Three coordinated parts, all low-overhead enough to run in every
production binary:

  1. **Sampling wall-clock profiler** (`SamplingProfiler`): a daemon
     thread samples `sys._current_frames()` at a configurable rate
     (default ~19 Hz — deliberately not a divisor of common 10/20/100 Hz
     timer periods, so periodic work doesn't alias into the samples),
     folds each thread's stack, tags it with the thread's *role*
     (derived from the thread names the subsystems assign at creation:
     device lane, prefetch, commit, HTTP handler, decrypt pool,
     flushers, SLO engine, ...) and aggregates into a bounded ring of
     fixed windows. Served as `GET /debug/profile` on every health
     listener in collapsed-stack (flamegraph.pl) format, with a JSON
     mode (`?format=json`) carrying per-role self/total percentages.
     The sampler measures its own cost and exports it
     (`janus_profiler_overhead_ratio`) — the overhead claim is a
     metric, not a promise.

  2. **Per-dispatch device cost ledger** (`DeviceCostLedger`): every
     supervised device region in the engine cache reports its wall time
     here, split by phase — `compile` (first call of an (op, bucket)),
     `execute` (dispatch), `h2d`/`d2h` (transfers) — keyed by
     (vdaf, op, bucket) with dispatch and row counts. The derived
     µs-per-report table (`janus_device_cost_us_per_report{op,phase}`)
     gives the PR 8 lane-busy ratio its denominator: what the busy time
     *buys* per report.

  3. **Boot-phase timeline** (`BootTimeline`): janus_main records named
     bring-up phases (imports → config → backend init → datastore →
     engine_warm_manifest (shape-manifest load) → engine_warm (the
     boot-budget AOT prewarm + legacy warmup) → listener up) as one
     contiguous sequence from the kernel-reported process start to
     /readyz-ready; served at `GET /debug/boot` and exported as
     `janus_boot_phase_seconds{phase}` so cold-start work (ROADMAP
     item 1) has a live baseline and a regression gate.

The frame/stack formatter here is shared with the device watchdog's
/statusz stalled-thread dumps, so the two renderings cannot diverge.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

from .statusz import register_status_provider

# ---------------------------------------------------------------------------
# Shared frame formatting: ONE definition of "how a Python frame renders"
# for the folded stacks, the JSON top-frames table and the device
# watchdog's stalled-thread dumps.
# ---------------------------------------------------------------------------


def frame_label(frame, lineno: bool = False) -> str:
    """Compact `module.function` label for one frame (`:lineno` of the
    currently executing line when requested — the watchdog dumps want
    it, the folded aggregation deliberately does not, or near-identical
    stacks would shatter into per-line singletons)."""
    code = frame.f_code
    mod = frame.f_globals.get("__name__") or os.path.basename(code.co_filename)
    label = f"{mod}.{code.co_name}"
    if lineno:
        label += f":{frame.f_lineno}"
    return label


def format_stack(frame, limit: int = 48, lineno: bool = True) -> list[str]:
    """Outermost-first frame labels of a live frame chain (the shared
    rendering behind folded samples and the /statusz
    `device_watchdog.stalled` stack dumps)."""
    out: list[str] = []
    while frame is not None and len(out) < limit:
        out.append(frame_label(frame, lineno=lineno))
        frame = frame.f_back
    out.reverse()
    return out


def validate_collapsed(text: str) -> list[str]:
    """Well-formedness errors of a collapsed-stack (flamegraph.pl)
    document: every non-empty line is `frame;frame;... count` with an
    integer count and non-empty, whitespace-free frame components (the
    sanitizer guarantees this even for hostile thread/frame names —
    scripts/scrape_check.py and the tests enforce it stays true)."""
    errors = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        stack, sep, count = line.rpartition(" ")
        if not sep or not count.isdigit():
            errors.append(f"line {i}: no trailing integer count: {line[:80]!r}")
            continue
        if not stack:
            errors.append(f"line {i}: empty stack: {line[:80]!r}")
            continue
        for comp in stack.split(";"):
            if not comp or any(c in comp for c in " \t\n\r"):
                errors.append(
                    f"line {i}: bad frame component {comp[:40]!r}: {line[:80]!r}"
                )
                break
    return errors


def fold_component(s: str) -> str:
    """Sanitize one folded-stack component (a role, thread or frame
    name): the collapsed format is `frame;frame;... count` per line, so
    semicolons, whitespace and newlines INSIDE a component would corrupt
    the fold — a hostile thread name must render inert."""
    return "".join("_" if c in ";\n\r\t " or ord(c) < 0x20 else c for c in str(s)) or "_"


# ---------------------------------------------------------------------------
# Thread-role taxonomy: prefix match over the names the subsystems
# assign where their threads are created (docs/OBSERVABILITY.md carries
# the same table). First match wins — order longest/most specific first.
# ---------------------------------------------------------------------------

ROLE_PREFIXES: tuple[tuple[str, str], ...] = (
    ("device-lane-gauge", "flusher"),   # low-cadence gauge refresher
    ("device-lane", "device_lane"),     # the pipeline's serialized lane
    ("device-watchdog", "device_lane"), # supervised dispatches run here
    ("mesh-dispatch", "device_lane"),   # single-controller mesh enqueue lane
    ("step-read", "prefetch"),          # pipeline read/staging stage
    ("step-commit", "commit"),          # pipeline commit stage
    ("step-http", "http_client"),       # pipeline helper-HTTP stage
    ("dap-handler", "http_handler"),    # bounded HTTP handler pool
    ("ingest-decrypt", "decrypt_pool"),
    ("ingest-decode", "decode_pool"),
    ("report-writer", "flusher"),       # upload group-commit flusher
    ("resident-flusher", "flusher"),
    ("upload-journal-replay", "flusher"),
    ("chrome-trace-flush", "flusher"),
    ("slo-engine", "slo_engine"),
    ("health-sampler", "sampler"),
    ("datastore-supervisor", "supervisor"),
    ("engine-canary", "engine_warm"),
    ("engine-warmup", "engine_warm"),
    ("dap-listener", "listener"),       # accept loops (normalized names)
    ("health-listener", "listener"),
    ("api-listener", "listener"),
    ("interop-listener", "listener"),
    # the interop runner STEPS jobs (real aggregation work), so it must
    # not fold into the accept-loop role
    ("interop-runner", "other"),
    ("gc-loop", "gc"),
    ("janus-profiler", "profiler"),
    ("flight-recorder", "flight"),      # telemetry history snapshotter
    ("MainThread", "main"),
)


def thread_role(name: str) -> str:
    for prefix, role in ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    return "other"


# Leaf frames in these modules are parked waits (lock/queue/socket/
# sleep callers), not work: a wall-clock sample whose leaf lands here
# counts toward the role's TOTAL share but not its SELF share, so
# "device_lane 90% total / 5% self" reads as an idle lane, not a busy
# one. (C-level blocking shows the Python caller as the leaf, which is
# why this is a module heuristic rather than a function list —
# concurrent.futures.thread is here because an idle pool worker's
# queue.get is C-level SimpleQueue, leaving `_worker` itself as the
# Python leaf.)
_WAIT_MODULES = frozenset(
    (
        "threading",
        "queue",
        "selectors",
        "socket",
        "ssl",
        "socketserver",
        "subprocess",
        "concurrent.futures.thread",
    )
)


def _is_wait_leaf(label: str) -> bool:
    return label.rpartition(".")[0] in _WAIT_MODULES


@dataclass
class ProfilerConfig:
    """YAML `profiler:` stanza on CommonConfig (enabled by default in
    every binary via janus_main)."""

    enabled: bool = True
    # sampling rate; ~19 Hz default (prime-ish, anti-aliasing)
    hz: float = 19.0
    # fixed aggregation window length and the bounded ring of retained
    # windows: /debug/profile aggregates current + retained (so the
    # served view covers ~window_secs * (windows + 1) of history)
    window_secs: float = 30.0
    windows: int = 10
    max_stack_depth: int = 48

    @classmethod
    def from_dict(cls, d: dict | None) -> "ProfilerConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            hz=float(d.get("hz", 19.0)),
            window_secs=float(d.get("window_secs", 30.0)),
            windows=int(d.get("windows", 10)),
            max_stack_depth=int(d.get("max_stack_depth", 48)),
        )


class _Window:
    __slots__ = ("start_unix", "passes", "samples", "stacks", "busy_s", "span_s")

    def __init__(self, start_unix: float):
        self.start_unix = start_unix
        self.passes = 0
        self.samples = 0  # thread-stacks sampled
        # {(role, frames tuple outermost-first): count}
        self.stacks: dict[tuple, int] = {}
        self.busy_s = 0.0  # sampler's own wall time inside this window
        self.span_s = 0.0  # wall covered by this window (set at rotation)


class SamplingProfiler:
    """See the module docstring. One instance per process (`PROFILER`),
    started by `install_profiler` from janus_main; tests construct their
    own."""

    def __init__(self, cfg: ProfilerConfig | None = None):
        self.cfg = cfg or ProfilerConfig()
        self._lock = threading.Lock()
        self._current: _Window | None = None
        self._ring: deque[_Window] = deque(maxlen=max(1, self.cfg.windows))
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._threads_last = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        with self._lock:
            self._current = _Window(time.time())
        self._thread = threading.Thread(
            target=self._loop, name="janus-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
        self._thread = None

    # -- sampling ------------------------------------------------------
    def _loop(self) -> None:
        interval = 1.0 / max(0.1, self.cfg.hz)
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:  # the sampler must never die of one pass
                import logging

                logging.getLogger(__name__).exception("profiler sampling pass failed")

    def sample_once(self) -> int:
        """One sampling pass (also driven directly by tests): fold every
        other thread's stack into the current window. Returns the number
        of thread-stacks sampled."""
        from . import metrics

        t0 = time.perf_counter()
        own = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        depth = self.cfg.max_stack_depth
        sampled = 0
        entries = []
        for ident, frame in frames.items():
            if ident == own:
                continue
            name = names.get(ident, f"ident-{ident}")
            stack = tuple(format_stack(frame, limit=depth, lineno=False))
            if not stack:
                continue
            entries.append((thread_role(name), stack))
            sampled += 1
        busy = time.perf_counter() - t0
        now = time.time()
        with self._lock:
            self._maybe_rotate_locked(now)
            w = self._current
            if w is None:
                w = self._current = _Window(now)
            w.passes += 1
            w.samples += sampled
            w.busy_s += busy
            for key in entries:
                w.stacks[key] = w.stacks.get(key, 0) + 1
            self._threads_last = sampled
            overhead = self._overhead_ratio_locked()
        metrics.profiler_samples_total.add()
        metrics.profiler_threads.set(float(sampled))
        metrics.profiler_overhead_ratio.set(overhead)
        return sampled

    def _maybe_rotate_locked(self, now: float) -> None:
        w = self._current
        if w is not None and now - w.start_unix >= self.cfg.window_secs:
            w.span_s = now - w.start_unix
            self._ring.append(w)
            self._current = _Window(now)

    def _overhead_ratio_locked(self) -> float:
        """Measured sampler cost as a fraction of the wall time covered
        by the retained windows (0.0 while the sampler is off)."""
        busy = sum(w.busy_s for w in self._ring)
        span = sum(w.span_s for w in self._ring)
        w = self._current
        if w is not None:
            busy += w.busy_s
            span += time.time() - w.start_unix
        if span <= 0:
            return 0.0
        return busy / span

    # -- aggregation & rendering --------------------------------------
    def _aggregate_locked(self) -> tuple[dict, int, int]:
        """(stacks, samples, passes) merged across ring + current."""
        stacks: dict[tuple, int] = {}
        samples = passes = 0
        for w in list(self._ring) + ([self._current] if self._current else []):
            samples += w.samples
            passes += w.passes
            for key, c in w.stacks.items():
                stacks[key] = stacks.get(key, 0) + c
        return stacks, samples, passes

    def collapsed(self) -> str:
        """flamegraph.pl folded format: `role;frame;...;frame count`
        per line, root first, every component sanitized so hostile
        thread/frame names cannot corrupt the fold."""
        with self._lock:
            stacks, _, _ = self._aggregate_locked()
        lines = [
            ";".join(fold_component(c) for c in (role,) + frames) + f" {count}"
            for (role, frames), count in sorted(
                stacks.items(), key=lambda kv: -kv[1]
            )
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def profile_json(self, top: int = 30) -> dict:
        """The `?format=json` payload: per-role self/total percentages
        (self excludes parked-wait leaves) and the top frames by self
        and total sample counts."""
        with self._lock:
            stacks, samples, passes = self._aggregate_locked()
            overhead = self._overhead_ratio_locked()
            threads_last = self._threads_last
            windows_retained = len(self._ring)
        roles: dict[str, dict] = {}
        frame_self: dict[str, int] = {}
        frame_total: dict[str, int] = {}
        for (role, frames), count in stacks.items():
            r = roles.setdefault(role, {"samples": 0, "self_samples": 0})
            r["samples"] += count
            leaf = frames[-1]
            if not _is_wait_leaf(leaf):
                r["self_samples"] += count
            frame_self[leaf] = frame_self.get(leaf, 0) + (
                0 if _is_wait_leaf(leaf) else count
            )
            for f in set(frames):
                frame_total[f] = frame_total.get(f, 0) + count
        denom = max(1, samples)
        for r in roles.values():
            r["total_pct"] = round(100.0 * r["samples"] / denom, 2)
            r["self_pct"] = round(100.0 * r["self_samples"] / denom, 2)
        top_frames = [
            {
                "frame": f,
                "self": frame_self.get(f, 0),
                "total": frame_total[f],
                "self_pct": round(100.0 * frame_self.get(f, 0) / denom, 2),
                "total_pct": round(100.0 * frame_total[f] / denom, 2),
            }
            for f in sorted(
                frame_total, key=lambda f: (-frame_self.get(f, 0), -frame_total[f])
            )[:top]
        ]
        return {
            "enabled": self.running,
            "hz": self.cfg.hz,
            "window_secs": self.cfg.window_secs,
            "windows_retained": windows_retained,
            "windows_cap": self._ring.maxlen,
            "passes": passes,
            "samples": samples,
            "threads_last_pass": threads_last,
            "overhead_ratio": round(overhead, 6),
            "roles": {k: roles[k] for k in sorted(roles)},
            "top_frames": top_frames,
        }

    def status(self) -> dict:
        """The compact /statusz `profile` section: enabled state,
        per-role CPU shares and the top frames by self time."""
        doc = self.profile_json(top=5)
        return {
            "enabled": doc["enabled"],
            "hz": doc["hz"],
            "passes": doc["passes"],
            "samples": doc["samples"],
            "overhead_ratio": doc["overhead_ratio"],
            "roles": {
                role: {"total_pct": r["total_pct"], "self_pct": r["self_pct"]}
                for role, r in doc["roles"].items()
            },
            "top_frames": doc["top_frames"],
        }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ring.clear()
            self._current = _Window(time.time()) if self.running else None


# process-wide instance: always present (so /debug/profile and the
# statusz section answer a well-formed disabled document), started by
# install_profiler
PROFILER = SamplingProfiler()


def install_profiler(cfg: ProfilerConfig | None = None) -> SamplingProfiler:
    """Install + start the process profiler from the YAML `profiler:`
    stanza (janus_main). Replaces any running instance."""
    global PROFILER
    cfg = cfg or ProfilerConfig()
    PROFILER.stop()
    PROFILER = SamplingProfiler(cfg)
    if cfg.enabled:
        PROFILER.start()
    return PROFILER


def uninstall_profiler() -> None:
    """Stop the process profiler (teardown hook; the instance stays so
    the endpoints keep answering a well-formed disabled document)."""
    PROFILER.stop()


def profile_collapsed() -> str:
    return PROFILER.collapsed()


def profile_json() -> dict:
    return PROFILER.profile_json()


# ---------------------------------------------------------------------------
# Per-dispatch device cost ledger
# ---------------------------------------------------------------------------

COST_PHASES = ("compile", "execute", "h2d", "d2h")


class DeviceCostLedger:
    """Cumulative device-path cost per (vdaf, op, bucket), split by
    phase, with dispatch and row counts — fed by the engine cache's
    choke points (`_record_dispatch` for compile/execute + rows, the
    put/fetch span hooks for h2d/d2h, the supervised resident fetches).
    Derives the live `janus_device_cost_us_per_report{op,phase}` table:
    for an op, phase seconds summed over (vdaf, bucket) divided by the
    op's total rows."""

    def __init__(self):
        self._lock = threading.Lock()
        # {(vdaf, op, bucket): {"dispatches": n, "rows": n, <phase>_s...}}
        self._entries: dict[tuple, dict] = {}
        self._op_rows: dict[str, int] = {}
        self._op_phase_s: dict[tuple[str, str], float] = {}

    def record(
        self,
        vdaf: str,
        op: str,
        bucket: int,
        phase: str,
        seconds: float,
        rows: int = 0,
        dispatches: int = 0,
    ) -> None:
        if phase not in COST_PHASES:
            raise ValueError(f"unknown cost phase {phase!r}")
        from . import metrics

        key = (str(vdaf), str(op), int(bucket))
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = self._entries[key] = {
                    "dispatches": 0,
                    "rows": 0,
                    **{f"{p}_s": 0.0 for p in COST_PHASES},
                }
            ent["dispatches"] += dispatches
            ent["rows"] += rows
            ent[f"{phase}_s"] += seconds
            self._op_rows[op] = self._op_rows.get(op, 0) + rows
            self._op_phase_s[(op, phase)] = (
                self._op_phase_s.get((op, phase), 0.0) + seconds
            )
            op_rows = self._op_rows[op]
            gauge_updates = (
                [
                    (p, self._op_phase_s.get((op, p), 0.0))
                    for p in COST_PHASES
                ]
                if op_rows > 0
                else []
            )
        metrics.device_cost_seconds_total.add(seconds, op=op, phase=phase)
        for p, total_s in gauge_updates:
            metrics.device_cost_us_per_report.set(
                total_s / op_rows * 1e6, op=op, phase=p
            )

    def us_per_report(self) -> dict:
        """{op: {phase: µs/report}} for ops with recorded rows (the
        bench rider and the statusz attribution table)."""
        with self._lock:
            out: dict = {}
            for (op, phase), s in self._op_phase_s.items():
                rows = self._op_rows.get(op, 0)
                if rows > 0:
                    out.setdefault(op, {})[phase] = round(s / rows * 1e6, 3)
            return {op: dict(sorted(v.items())) for op, v in sorted(out.items())}

    def status(self) -> dict:
        """The /statusz `device_cost` section."""
        with self._lock:
            entries = [
                {
                    "vdaf": vdaf,
                    "op": op,
                    "bucket": bucket,
                    "dispatches": ent["dispatches"],
                    "rows": ent["rows"],
                    **{
                        f"{p}_s": round(ent[f"{p}_s"], 6)
                        for p in COST_PHASES
                    },
                }
                for (vdaf, op, bucket), ent in sorted(self._entries.items())
            ]
        return {"entries": entries, "us_per_report": self.us_per_report()}

    def reset_for_tests(self) -> None:
        with self._lock:
            self._entries.clear()
            self._op_rows.clear()
            self._op_phase_s.clear()


DEVICE_COST = DeviceCostLedger()


# h2d/d2h wall time rides the existing engine put/fetch spans via the
# span-hook registry (trace.register_span_hook): the span boundaries
# ARE the transfer boundaries (engine_cache keeps the blocking
# conversions inside them), so the ledger and the Chrome trace measure
# the same thing by construction. The `bucket` span arg (added at the
# engine call sites) keys the per-bucket row of the table.
_TRANSFER_SPANS = {
    "engine.helper_init.put": ("helper_init", "h2d"),
    "engine.helper_init.fetch": ("helper_init", "d2h"),
    "engine.leader_init.put": ("leader_init", "h2d"),
    "engine.leader_init.put_all_async": ("leader_init", "h2d"),
    "engine.leader_init.fetch": ("leader_init", "d2h"),
    "engine.leader_init.fetch_seed": ("leader_init", "d2h"),
    "engine.leader_init.fetch_ver": ("leader_init", "d2h"),
    "engine.leader_init.fetch_part": ("leader_init", "d2h"),
}


def _register_transfer_hooks() -> None:
    from .trace import register_span_hook

    def make_hook(op: str, phase: str):
        def hook(dur_s: float, args: dict) -> None:
            try:
                bucket = int(args.get("bucket") or 0)
            except (TypeError, ValueError):
                bucket = 0
            DEVICE_COST.record(
                str(args.get("vdaf", "")), op, bucket, phase, dur_s
            )

        return hook

    for name, (op, phase) in _TRANSFER_SPANS.items():
        register_span_hook(name, make_hook(op, phase))


_register_transfer_hooks()


# ---------------------------------------------------------------------------
# Boot-phase timeline
# ---------------------------------------------------------------------------


class BootTimeline:
    """Contiguous named bring-up phases from the kernel-reported process
    start: `phase_done(name)` closes the phase running since the
    previous mark, `mark_ready()` seals the record at the moment the
    process turns servable (the health listener is up and /readyz
    answers), so the recorded phases sum EXACTLY to the
    process-start → ready wall time. Phases reported after ready (a
    binary's run() body booting late subsystems — journal scan, DAP
    listener) append flagged `late` and are excluded from that sum."""

    def __init__(self, start_unix: float | None = None):
        if start_unix is None:
            from .metrics import _process_start_time

            start_unix = _process_start_time()
        self.start_unix = start_unix
        self._lock = threading.Lock()
        self._phases: list[dict] = []
        self._last_mark = start_unix
        self.ready_unix: float | None = None

    def phase_done(self, name: str) -> float:
        """Close the phase running since the previous mark; returns its
        duration. Also exports janus_boot_phase_seconds{phase}."""
        from . import metrics

        now = time.time()
        with self._lock:
            start = self._last_mark
            seconds = max(0.0, now - start)
            self._phases.append(
                {
                    "phase": str(name),
                    "start_s": round(start - self.start_unix, 6),
                    "end_s": round(now - self.start_unix, 6),
                    "seconds": round(seconds, 6),
                    **({"late": True} if self.ready_unix is not None else {}),
                }
            )
            self._last_mark = now
        metrics.boot_phase_seconds.set(seconds, phase=str(name))
        return seconds

    def mark_ready(self) -> None:
        """Seal the boot record (idempotent; first call wins)."""
        with self._lock:
            if self.ready_unix is None:
                self.ready_unix = time.time()
                self._last_mark = self.ready_unix

    def snapshot(self) -> dict:
        """The GET /debug/boot payload."""
        with self._lock:
            phases = [dict(p) for p in self._phases]
            ready = self.ready_unix
        boot = [p for p in phases if not p.get("late")]
        doc = {
            "started_unix": self.start_unix,
            "ready": ready is not None,
            "phases": phases,
            "boot_phases_sum_s": round(sum(p["seconds"] for p in boot), 6),
        }
        if ready is not None:
            doc["ready_unix"] = ready
            doc["total_s"] = round(ready - self.start_unix, 6)
        return doc

    def reset_for_tests(self, start_unix: float | None = None) -> None:
        with self._lock:
            self._phases.clear()
            self.start_unix = start_unix if start_unix is not None else time.time()
            self._last_mark = self.start_unix
            self.ready_unix = None


BOOT = BootTimeline()


def boot_snapshot() -> dict:
    return BOOT.snapshot()


# /statusz sections: the profiler summary and the device-cost table on
# every binary (registered at import — binary_utils imports this
# module, so every health listener carries them; both answer
# well-formed empty/disabled documents before anything runs)
register_status_provider("profile", lambda: PROFILER.status())
register_status_provider("device_cost", DEVICE_COST.status)
