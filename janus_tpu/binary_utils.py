"""Binary harness shared by the five processes.

Equivalent of reference aggregator/src/binary_utils.rs: `janus_main`
(config parse -> trace subscriber -> metrics -> datastore -> run),
the /healthz listener (also serving /metrics Prometheus text), and
SIGTERM -> Stopper graceful shutdown (binary_utils.rs:40-120,
docs/DEPLOYING.md:33-39).

Datastore keys come from --datastore-keys or the DATASTORE_KEYS env
var (comma-separated base64, first key is primary), matching the
reference's k8s-secret pathway.
"""

from __future__ import annotations

import argparse
import base64
import logging
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .aggregator import prewarm as prewarm_mod
from .aggregator import shape_manifest as shape_manifest_mod
from .aggregator.job_driver import Stopper
from .config import CommonConfig, load_config
from .core.time_util import RealClock
from .datastore.store import Crypter, open_datastore
from .metrics import REGISTRY
from .statusz import register_status_provider, render_statusz_html, status_snapshot
from .trace import install_trace_subscriber

log = logging.getLogger(__name__)

# Prometheus text exposition content type (version 0.0.4); the charset
# matters — label values may carry escaped non-ASCII task ids/errors.
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# The OpenMetrics exposition mode (?openmetrics=1 or Accept-negotiated):
# same families plus histogram exemplars and the # EOF terminator.
OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

# GET / on the health listener: a tiny discovery page so an operator
# pointed at a port can find every endpoint from a browser (previously
# a bare 404).
_INDEX_ENDPOINTS = (
    ("/healthz", "liveness (always 200 while the process runs)"),
    ("/readyz", "readiness (503 + JSON reasons while degraded)"),
    ("/metrics", "Prometheus text exposition"),
    ("/metrics?openmetrics=1", "OpenMetrics mode with trace exemplars"),
    ("/statusz", "process status snapshot (JSON; ?format=html)"),
    ("/alertz", "SLO burn-rate engine: alert state, budgets, evidence"),
    ("/debug/vars", "raw metrics-registry JSON dump"),
    ("/debug/traces", "flight recorder: recent spans, slow traces, digests"),
    ("/debug/profile", "continuous profiler: collapsed wall-clock stacks (flamegraph.pl)"),
    ("/debug/profile?format=json", "continuous profiler: per-role self/total shares"),
    ("/debug/boot", "boot-phase timeline (process start to /readyz ready)"),
    ("/debug/flight", "telemetry flight recorder: resource history, trend slopes, leak verdicts"),
    ("/debug/ledger", "report-flow conservation ledger: per-task balance, imbalance, breaches"),
)


def _render_index() -> bytes:
    import html as _html

    rows = "".join(
        f'<li><a href="{path}"><code>{_html.escape(path)}</code></a>'
        f" — {_html.escape(desc)}</li>"
        for path, desc in _INDEX_ENDPOINTS
    )
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>janus_tpu health listener</title>"
        "<style>body{font-family:monospace;margin:2em;}li{margin:0.3em 0;}</style>"
        "</head><body><h1>janus_tpu health listener</h1>"
        f"<ul>{rows}</ul>"
        "<p>POST /debug/profile?seconds=N opens an on-demand profiler "
        "capture window.</p></body></html>"
    ).encode()


# ---------------------------------------------------------------------------
# Readiness registry: /healthz is LIVENESS (the process is running —
# restarting it would not help), /readyz is READINESS (this replica can
# currently do useful work — take it out of rotation, don't kill it).
# A datastore outage fails readiness, never liveness: killing the pod
# would also kill the upload spill journal's replayer.
# ---------------------------------------------------------------------------

_readiness_lock = threading.Lock()
_readiness_checks: dict[str, object] = {}


def register_readiness_check(name: str, fn) -> None:
    """Register (or replace) a readiness check: `fn()` returns None
    when ready, or a human-readable reason string when not. A check
    that raises counts as not ready (with the exception as reason)."""
    with _readiness_lock:
        _readiness_checks[name] = fn


def unregister_readiness_check(name: str) -> None:
    with _readiness_lock:
        _readiness_checks.pop(name, None)


def readiness_snapshot() -> tuple[bool, dict]:
    """(ready, {check: reason}) across every registered check. No
    checks registered = ready (a binary without a datastore supervisor
    keeps its old semantics)."""
    with _readiness_lock:
        checks = dict(_readiness_checks)
    reasons: dict = {}
    for name, fn in sorted(checks.items()):
        try:
            reason = fn()
        except Exception as e:
            reason = f"readiness check failed: {type(e).__name__}: {e}"
        if reason:
            reasons[name] = str(reason)
    return not reasons, reasons


def parse_datastore_keys(raw: str) -> list[bytes]:
    keys = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        pad = "=" * (-len(part) % 4)
        keys.append(base64.urlsafe_b64decode(part + pad))
    if not keys:
        raise ValueError("at least one datastore key is required")
    for k in keys:
        if len(k) != 16:
            raise ValueError("datastore keys must be 16 bytes (AES-128-GCM)")
    return keys


def _split_hostport(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)


class BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a fixed handler pool instead of a
    thread per connection (docs/INGEST.md "Bounded serving"): accepted
    connections are served by at most `max_handler_threads` workers;
    excess connections wait in the accept backlog / pool queue rather
    than growing threads without limit. Both the DAP listener and the
    health/metrics listener use it."""

    # deep listen backlog: bursts of short-lived connections (load
    # generators, proxies that do not keep alive) otherwise overflow
    # the default 5-entry accept queue into client-visible resets
    request_queue_size = 128

    def __init__(self, addr, handler_cls, max_handler_threads: int = 32):
        import weakref
        from concurrent.futures import ThreadPoolExecutor

        super().__init__(addr, handler_cls)
        self._max_handler_threads = max(1, max_handler_threads)
        self._active_connections = 0
        self._active_lock = threading.Lock()
        # accept-time per connection (weak: entries vanish with the
        # socket) — socket objects define __slots__, so the stamp
        # cannot ride the object itself
        self._accept_times = weakref.WeakKeyDictionary()
        self._pool = ThreadPoolExecutor(
            max_workers=self._max_handler_threads, thread_name_prefix="dap-handler"
        )

    def queue_age_s(self, request) -> float | None:
        """Seconds `request` (a connection socket) waited between
        accept and the handler picking it up, once: the entry is
        consumed, so later keep-alive requests on the same connection —
        whose wait is the CLIENT's idle time, not ours — read None.
        Handlers charge this against a request's propagated deadline
        (docs/ROBUSTNESS.md deadline contract)."""
        t = self._accept_times.pop(request, None)
        return None if t is None else time.monotonic() - t

    @property
    def saturated(self) -> bool:
        """Every pool worker is occupied by a connection. Handlers use
        this to drop HTTP keep-alive (`Connection: close` after the
        in-flight response): a persistent connection pins its worker
        for the connection's lifetime, so at saturation idle-but-open
        clients would otherwise starve every later connection without
        even a 429 reaching them."""
        return self._active_connections >= self._max_handler_threads

    def process_request(self, request, client_address):
        # queue-entry stamp (docs/ROBUSTNESS.md deadline contract):
        # handlers charge the pool-queue wait against a request's
        # propagated deadline — a request that expired while queued is
        # shed before any crypto
        try:
            self._accept_times[request] = time.monotonic()
        except TypeError:  # exotic non-weakref-able socket impls
            pass
        try:
            self._pool.submit(self._process_in_pool, request, client_address)
        except RuntimeError:  # pool already shut down (server closing)
            self.shutdown_request(request)

    def _process_in_pool(self, request, client_address):
        with self._active_lock:
            self._active_connections += 1
        try:
            self.finish_request(request, client_address)
        except Exception:
            self.handle_error(request, client_address)
        finally:
            with self._active_lock:
                self._active_connections -= 1
            self.shutdown_request(request)

    def server_close(self):
        super().server_close()
        self._pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# On-demand profiler capture (POST /debug/profile?seconds=N): one
# window runs jax.profiler.trace (device timeline, loadable in
# Perfetto/TensorBoard) plus a temporary host Chrome-trace writer, and
# answers with the artifact paths. Guarded: concurrent captures 409,
# the window is clamped.
# ---------------------------------------------------------------------------

PROFILE_MIN_SECONDS = 0.1
PROFILE_MAX_SECONDS = 60.0
_profile_lock = threading.Lock()


class ProfileBusy(RuntimeError):
    """A capture window is already open."""


def capture_profile(seconds: float, out_dir: str | None = None) -> dict:
    """Open a capture window of `seconds` (clamped to
    [PROFILE_MIN_SECONDS, PROFILE_MAX_SECONDS]); raises ProfileBusy if
    one is already open. Returns the artifact paths: the host
    Chrome-trace JSON always; the jax.profiler trace dir when the
    profiler starts (absent on backends without one)."""
    import tempfile
    import time as _time

    from .trace import scoped_chrome_trace

    if not _profile_lock.acquire(blocking=False):
        raise ProfileBusy("a profile capture is already in progress")
    try:
        seconds = min(max(float(seconds), PROFILE_MIN_SECONDS), PROFILE_MAX_SECONDS)
        out_dir = out_dir or tempfile.mkdtemp(prefix="janus-profile-")
        os.makedirs(out_dir, exist_ok=True)
        host_trace = os.path.join(out_dir, "host-trace.json")
        device_dir = os.path.join(out_dir, "device")
        device_started = False
        device_error = None
        try:
            import jax

            jax.profiler.start_trace(device_dir)
            device_started = True
        except Exception as e:  # no profiler on this backend — host-only
            device_error = f"{type(e).__name__}: {e}"
        try:
            with scoped_chrome_trace(host_trace):
                _time.sleep(seconds)
        finally:
            if device_started:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception as e:
                    device_started = False
                    device_error = f"{type(e).__name__}: {e}"
        out = {"seconds": seconds, "host_chrome_trace": host_trace}
        if device_started:
            out["device_trace_dir"] = device_dir
        if device_error is not None:
            out["device_profiler_error"] = device_error
        return out
    finally:
        _profile_lock.release()


class HealthServer:
    """The per-process introspection listener:

      GET  /healthz                  -> 200 (liveness: always, while
                                        the process runs)
      GET  /readyz                   -> 200 when every registered
                                        readiness check passes; 503
                                        with a JSON reason map when
                                        degraded (datastore down,
                                        upload journal full)
      GET  /metrics                  -> Prometheus text exposition
      GET  /statusz                  -> JSON status snapshot (HTML with
                                        ?format=html or Accept: text/html)
      GET  /debug/vars               -> JSON dump of the metrics registry
      POST /debug/profile?seconds=N  -> on-demand profiler capture

    (reference serves /healthz from binary_utils.rs and metrics via the
    OTel Prometheus exporter, metrics.rs:53-80; statusz/debug follow
    the usual *z-page convention)."""

    def __init__(self, addr: str):
        host, port = _split_hostport(addr)

        class Handler(BaseHTTPRequestHandler):
            def _send(self, status: int, ctype: str, body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                import json as _json
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                query = dict(parse_qsl(parts.query))
                if parts.path == "/healthz":
                    self._send(200, "text/plain", b"")
                elif parts.path in ("/", "/index.html"):
                    self._send(200, "text/html; charset=utf-8", _render_index())
                elif parts.path == "/alertz":
                    # in-process SLO burn-rate engine state (installed
                    # by janus_main from the YAML `slo:` stanza; a
                    # process without one answers a well-formed
                    # disabled document)
                    from .slo import alertz_snapshot

                    self._send(
                        200,
                        "application/json",
                        _json.dumps(alertz_snapshot(), default=str).encode(),
                    )
                elif parts.path == "/readyz":
                    ready, reasons = readiness_snapshot()
                    body = {"ready": ready}
                    if reasons:
                        body["reasons"] = reasons
                    self._send(
                        200 if ready else 503,
                        "application/json",
                        _json.dumps(body).encode(),
                    )
                elif parts.path == "/metrics":
                    # OpenMetrics mode (exemplar syntax + # EOF) via
                    # ?openmetrics=1 or Accept negotiation; the default
                    # scrape's bytes are unaffected by stored exemplars
                    openmetrics = query.get("openmetrics") == "1" or (
                        "application/openmetrics-text"
                        in (self.headers.get("Accept") or "")
                    )
                    self._send(
                        200,
                        OPENMETRICS_CONTENT_TYPE if openmetrics else METRICS_CONTENT_TYPE,
                        REGISTRY.render(openmetrics=openmetrics).encode(),
                    )
                elif parts.path == "/statusz":
                    snap = status_snapshot()
                    wants_html = query.get("format") == "html" or "text/html" in (
                        self.headers.get("Accept") or ""
                    )
                    if wants_html:
                        self._send(
                            200,
                            "text/html; charset=utf-8",
                            render_statusz_html(snap).encode(),
                        )
                    else:
                        self._send(
                            200,
                            "application/json",
                            _json.dumps(snap, indent=2, default=str).encode(),
                        )
                elif parts.path == "/debug/vars":
                    self._send(
                        200, "application/json", _json.dumps(REGISTRY.snapshot()).encode()
                    )
                elif parts.path == "/debug/profile":
                    # always-on sampling profiler: collapsed-stack
                    # (flamegraph.pl) folded format by default, JSON
                    # role/frame shares with ?format=json (the POST
                    # form of this path remains the on-demand
                    # jax.profiler capture window)
                    from .profiler import profile_collapsed, profile_json

                    wants_json = query.get("format") == "json" or (
                        "application/json" in (self.headers.get("Accept") or "")
                    )
                    if wants_json:
                        self._send(
                            200,
                            "application/json",
                            _json.dumps(profile_json(), default=str).encode(),
                        )
                    else:
                        self._send(
                            200,
                            "text/plain; charset=utf-8",
                            profile_collapsed().encode(),
                        )
                elif parts.path == "/debug/boot":
                    # one-shot boot-phase timeline (janus_main records
                    # the phases; sums to process-start -> ready)
                    from .profiler import boot_snapshot

                    self._send(
                        200,
                        "application/json",
                        _json.dumps(boot_snapshot(), default=str).encode(),
                    )
                elif parts.path == "/debug/traces":
                    # always-on flight recorder: recent completed spans,
                    # captured slow traces, per-name latency digests
                    # (?limit=N bounds the recent list)
                    from .trace import flight_recorder

                    try:
                        limit = max(1, min(int(query.get("limit", "100")), 10_000))
                    except ValueError:
                        limit = 100
                    self._send(
                        200,
                        "application/json",
                        _json.dumps(
                            flight_recorder().snapshot(recent_limit=limit),
                            default=str,
                        ).encode(),
                    )
                elif parts.path == "/debug/flight":
                    # telemetry flight recorder: recent resource/metric
                    # history + live trend analysis (?window_secs=N
                    # narrows the judged window, ?max_points=N bounds
                    # the snapshot list)
                    from .flight_recorder import flight_document

                    try:
                        window_s = float(query["window_secs"])
                    except (KeyError, ValueError):
                        window_s = None
                    try:
                        max_points = max(1, min(int(query.get("max_points", "500")), 10_000))
                    except ValueError:
                        max_points = 500
                    self._send(
                        200,
                        "application/json",
                        _json.dumps(
                            flight_document(window_s=window_s, max_points=max_points),
                            default=str,
                        ).encode(),
                    )
                elif parts.path == "/debug/ledger":
                    # report-flow conservation ledger: latest complete
                    # per-task balance document (torn-read tolerant —
                    # the evaluator hands out the last COMPLETE doc)
                    from .ledger import ledger_document

                    self._send(
                        200,
                        "application/json",
                        _json.dumps(ledger_document(), default=str).encode(),
                    )
                else:
                    self._send(404, "text/plain", b"not found")

            def do_POST(self):  # noqa: N802
                import json as _json
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                if parts.path != "/debug/profile":
                    self._send(404, "text/plain", b"not found")
                    return
                query = dict(parse_qsl(parts.query))
                try:
                    seconds = float(query.get("seconds", "2"))
                except ValueError:
                    self._send(400, "text/plain", b"seconds must be a number")
                    return
                try:
                    result = capture_profile(seconds)
                except ProfileBusy as e:
                    self._send(
                        409,
                        "application/json",
                        _json.dumps({"error": str(e)}).encode(),
                    )
                    return
                except Exception:
                    log.exception("profile capture failed")
                    self._send(500, "text/plain", b"profile capture failed")
                    return
                self._send(200, "application/json", _json.dumps(result).encode())

            def log_message(self, fmt, *args):
                pass

        # small fixed pool: scrapes and probes are cheap, and the
        # listener must never be a thread-growth vector either
        self._srv = BoundedThreadingHTTPServer((host, port), Handler, max_handler_threads=4)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="health-listener", daemon=True
        )

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "HealthServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def setup_signal_handler(stopper: Stopper) -> None:
    """SIGTERM/SIGINT -> cooperative stop (binary_utils.rs
    setup_signal_handler). Only callable from the main thread."""

    def handle(signum, frame):
        log.info("received signal %s, shutting down", signum)
        stopper.stop()
        # release threads parked by hang failpoints (a modeled device
        # wedge must not outlive the process's intent to exit)
        from . import failpoints

        failpoints.release_hangs()

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Turn on the persistent XLA compilation cache via jax.config (env
    vars are a no-op once jax is preimported — sitecustomize does).
    One shared helper for bench.py, the measurement scripts, the
    dryrun entry, and the CLI precompile; the serving binaries
    configure theirs from CommonConfig.compilation_cache_dir (ON by
    default — `compilation_cache_dir: null` is the explicit
    off-switch)."""
    import jax

    resolved = os.path.expanduser(cache_dir or "~/.cache/jax_comp_cache")
    jax.config.update("jax_compilation_cache_dir", resolved)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    # statusz `engine_prewarm` section + the prewarm hit/miss split
    # read the live cache dir from here
    prewarm_mod.note_compile_cache(resolved)


def warmup_engines_background(ds, buckets=None, manifest=None) -> "threading.Thread":
    """Ahead-of-time bucket compilation OFF the boot path (VERDICT r3
    weak #8: a fresh deployment's first job on a new batch bucket still
    stalled minutes). Serving starts immediately; a daemon thread warms
    each configured bucket in ascending order, so the small buckets
    (interactive traffic) compile first and big job buckets follow.
    `manifest` has warmup_engines' semantics — janus_main passes
    _NO_DEDUPE when the manifest prewarm did not run."""
    import threading

    buckets = sorted(buckets or (None,), key=lambda b: b or 0)

    def work():
        for b in buckets:
            warmup_engines(ds, batch=b, manifest=manifest)

    t = threading.Thread(target=work, name="engine-warmup", daemon=True)
    t.start()
    return t


_NO_DEDUPE = object()  # warmup sentinel: skip NO geometry (the manifest
# prewarm did not run, so nothing "owns" the covered ones)


def warmup_engines(ds, batch: int | None = None, manifest=None) -> dict:
    """Compile the device engine steps for every provisioned task before
    serving traffic (cold-start mitigation: a cold aggregator otherwise
    stalls for minutes on first request per task). With the persistent
    compilation cache, restarts reduce this to disk loads.

    `batch` selects the batch size to warm (engines compile per
    power-of-two jit bucket). Without it, each task warms the sizes of
    its PENDING aggregation jobs — the geometry the next driver pass
    will actually dispatch — falling back to MIN_BUCKET only when
    there is no pending work to learn from. Geometries the shape
    manifest already covers are SKIPPED (counted
    `outcome="skipped_covered"`): the manifest-driven prewarm owns
    them, so warm-up work is never duplicated — pass
    `manifest=_NO_DEDUPE` when the prewarm did NOT run (disabled /
    failed), so a covered-but-unwarmed geometry still warms. Returns a
    summary dict ({"warmed": [(task_id, bucket)], "skipped_covered": n})."""
    import numpy as np

    from . import metrics
    from .aggregator import shape_manifest
    from .aggregator.engine_cache import (
        MIN_BUCKET,
        HostEngineCache,
        bucket_size,
        engine_cache,
    )
    from .vdaf.testing import make_report_batch, random_measurements

    if manifest is _NO_DEDUPE:
        manifest = None
    elif manifest is None:
        manifest = shape_manifest.installed()
    tasks = ds.run_tx(lambda tx: tx.get_tasks(), "warmup_list_tasks")
    pending: dict[bytes, list[int]] = {}
    if batch is None:
        try:
            pending = ds.run_tx(
                lambda tx: tx.get_pending_aggregation_job_sizes(), "warmup_job_sizes"
            )
        except Exception:
            log.warning(
                "pending aggregation job sizes unavailable; warming the "
                "minimum bucket",
                exc_info=True,
            )
    # ops a task-bucket warm compiles; a bucket is skipped only when the
    # manifest covers ALL of them (a partial warm would still pay the
    # leader leg the aggregate warm needs)
    warm_ops = ("leader_init", "helper_init", "aggregate")
    result: dict = {"warmed": [], "skipped_covered": 0}
    # warm dispatches are infrastructure, not the serving path a chaos
    # schedule drills: keep armed failpoints inert so `after=K` anchors
    # stay pinned to SERVING dispatch counts (failpoints.suppressed)
    from . import failpoints

    with failpoints.suppressed():
        for task in tasks:
            if task.vdaf.kind.startswith("fake") or task.vdaf.kind == "poplar1":
                continue  # fakes and host-side Poplar1 have no device engine
            if batch is not None:
                sizes = [int(batch)]
            else:
                # dedupe pending job sizes by their jit bucket (the compile
                # unit), keep ascending so interactive sizes warm first,
                # and bound the set — one warm per bucket is enough
                by_bucket: dict[int, int] = {}
                for n in sorted(pending.get(task.task_id.data, [])):
                    by_bucket.setdefault(bucket_size(n), n)
                sizes = [by_bucket[b] for b in sorted(by_bucket)][:4] or [MIN_BUCKET]
            for warm_batch in sizes:
                b = bucket_size(warm_batch)
                inst_dict = task.vdaf.to_dict()
                try:
                    eng = engine_cache(task.vdaf, task.vdaf_verify_key)
                    if isinstance(eng, HostEngineCache):
                        continue  # host engines need no compile
                    # coverage is per mesh topology: a manifest recorded
                    # under a different (dp, sp, ndev) — another machine
                    # class, or a single-device run — names programs this
                    # process never dispatches, so it doesn't cover these
                    geometry = (
                        (eng.dp, eng.sp, eng._ndev) if eng.mesh is not None else None
                    )
                    if manifest is not None and all(
                        manifest.covers(inst_dict, op, b, geometry=geometry)
                        for op in warm_ops
                    ):
                        result["skipped_covered"] += 1
                        metrics.engine_prewarm_total.add(outcome="skipped_covered")
                        continue
                    rng = np.random.default_rng(0)
                    args, _ = make_report_batch(
                        task.vdaf, random_measurements(task.vdaf, warm_batch, rng), seed=0
                    )
                    nonce, parts, meas, proof, blind0, hseed, blind1 = args
                    out0, seed0, ver0, part0 = eng.leader_init(
                        nonce, parts, meas, proof, blind0
                    )
                    ok = np.ones(warm_batch, dtype=bool)
                    part0_l = (
                        part0
                        if part0 is not None
                        else np.zeros((warm_batch, 2), dtype=np.uint64)
                    )
                    eng.helper_init(nonce, parts, hseed, blind1, ver0, part0_l, ok)
                    if task.vdaf.kind == "sparse_sumvec":
                        # block-sparse tasks never dispatch the dense
                        # aggregate: warm the gather/scatter program the
                        # resident merge and the classic sparse path share
                        # (compile_key ("scatter_merge", bucket)) —
                        # aggregate_sparse is stateless, so no resident
                        # slot is polluted (docs/ARCHITECTURE.md
                        # "Block-sparse aggregation")
                        from .vdaf.registry import circuit_for
                        from .vdaf.testing import sparse_compact_batch
                        from .vdaf.wire import flat_scatter_indices

                        meas_pairs = random_measurements(task.vdaf, warm_batch, rng)
                        _, block_idx = sparse_compact_batch(task.vdaf, meas_pairs)
                        flat_idx = flat_scatter_indices(
                            block_idx, circuit_for(task.vdaf)
                        )
                        eng.aggregate_sparse(out0, ok, flat_idx)
                    else:
                        eng.aggregate(out0, ok)
                    result["warmed"].append((task.task_id, b))
                    log.info(
                        "warmed engines for task %s (%s) at bucket %d",
                        task.task_id, task.vdaf.kind, b,
                    )
                except Exception:
                    log.exception("engine warmup failed for task %s", task.task_id)
    return result


def janus_main(description: str, config_cls, run, argv=None, install_signals: bool = True):
    """Shared entry point (reference binary_utils.rs janus_main).

    `run(cfg, ds, stopper)` is the binary body; this harness owns config
    parsing, logging, the health/metrics listener, the datastore and
    signal handling.
    """
    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--config-file", required=True, help="YAML configuration file")
    parser.add_argument(
        "--datastore-keys",
        default=os.environ.get("DATASTORE_KEYS", ""),
        help="comma-separated base64url AES-128 keys (or DATASTORE_KEYS env)",
    )
    args = parser.parse_args(argv)

    # boot-phase timeline (docs/OBSERVABILITY.md "Continuous
    # profiling"): everything before this call — interpreter start,
    # janus_tpu/jax imports — is the "imports" phase; each later
    # phase_done closes the phase running since the previous mark, so
    # the phases tile process-start -> ready exactly
    from . import profiler as profiler_mod
    from .profiler import BOOT

    BOOT.phase_done("imports")

    cfg = load_config(args.config_file, config_cls)
    common: CommonConfig = cfg.common
    install_trace_subscriber(common.logging_config)

    # refresh janus_build_info with the YAML-configured backend (the
    # import-time registration guessed from the environment)
    from .metrics import register_build_info, set_replica_identity

    register_build_info(
        backend=common.jax_platform or os.environ.get("JAX_PLATFORMS")
    )

    # fleet replica identity (docs/ARCHITECTURE.md "Running a fleet"):
    # janus_replica_info carries it on every scrape; an EXPLICITLY
    # configured replica_id (YAML fleet: / JANUS_REPLICA_ID) also turns
    # on the per-replica labels of the job-driver/health-sampler/SLO
    # families and rides every trace as a resource attribute, so N
    # processes over one datastore stay attributable end to end.
    fleet = common.fleet
    replica_id = fleet.resolved_replica_id()
    set_replica_identity(
        replica_id=fleet.replica_id,
        shard_index=fleet.shard_index,
        shard_count=fleet.shard_count,
    )
    from .trace import set_resource_attributes

    set_resource_attributes(
        replica=replica_id,
        shard=f"{fleet.shard_index % max(1, fleet.shard_count)}/{fleet.shard_count}",
    )
    register_status_provider(
        "fleet",
        lambda: {
            "replica_id": replica_id,
            "configured": fleet.replica_id is not None,
            "shard_index": fleet.shard_index % max(1, fleet.shard_count),
            "shard_count": fleet.shard_count,
            "steal_after_secs": fleet.steal_after_secs,
        },
    )

    # fault injection: JANUS_FAILPOINTS env wins over the YAML
    # `failpoints:` key; unset/empty compiles every site to a no-op.
    # Always on /statusz so an operator can see at a glance whether a
    # process is running with injected faults (docs/ROBUSTNESS.md).
    from . import failpoints

    failpoints.configure_from_env(default=common.failpoints)
    register_status_provider("failpoints", failpoints.status)

    # device-path watchdog + quarantine knobs (registers the /statusz
    # `device_watchdog` section — abandoned-thread count + live stack
    # dumps of stalled dispatches — as an import side effect)
    from .aggregator import device_watchdog
    from .aggregator.engine_cache import EngineCache, shutdown_engines

    if "JANUS_WATCHDOG_ABANDONED_CAP" not in os.environ:
        # like the canary knobs below: the env var is the operator
        # override — applying the YAML/default over it would silently
        # kill the documented knob in every binary
        device_watchdog.configure(
            abandoned_thread_cap=common.watchdog_abandoned_thread_cap
        )
    if "JANUS_CANARY_DELAY_S" not in os.environ:
        EngineCache.QUARANTINE_CANARY_DELAY_SECS = common.quarantine_canary_delay_secs
    if "JANUS_CANARY_TIMEOUT_S" not in os.environ:
        EngineCache.QUARANTINE_CANARY_TIMEOUT_SECS = (
            common.quarantine_canary_timeout_secs
        )
    BOOT.phase_done("config")

    if common.jax_platform:
        os.environ["JAX_PLATFORMS"] = common.jax_platform
        try:
            import jax

            jax.config.update("jax_platforms", common.jax_platform)
        except Exception:
            log.exception("could not pin JAX platform %r", common.jax_platform)

    # persistent XLA compile cache: restart cold-start drops from
    # minutes (first jit of each engine step) to seconds. jax is
    # already imported by now (sitecustomize/transitive imports), so
    # env vars are a no-op — must go through jax.config. The `engine:`
    # stanza's compile_cache_dir overrides the top-level knob.
    compile_cache_dir = common.engine.compile_cache_dir or common.compilation_cache_dir
    if compile_cache_dir:
        try:
            enable_compile_cache(compile_cache_dir)
        except Exception:
            log.exception("could not enable the persistent compilation cache")
    # serialized-executable AOT cache rides beside the XLA cache: the
    # XLA cache skips recompiles, this skips the re-TRACE — the larger
    # half of a warm restart (docs/ARCHITECTURE.md "Cold-start and
    # prewarm"). JANUS_AOT_CACHE env: "0" off, a path relocates —
    # honored even with the XLA cache explicitly disabled.
    aot_env = os.environ.get("JANUS_AOT_CACHE")
    if aot_env != "0" and common.engine.aot_cache:
        aot_dir = aot_env or (
            os.path.join(os.path.expanduser(compile_cache_dir), "aot")
            if compile_cache_dir
            else None
        )
        if aot_dir:
            from .aggregator import aot_cache

            aot_cache.arm(aot_dir)

    # engine-layer knobs (YAML `engine:` stanza). Envs are the operator
    # override, same discipline as the watchdog knobs above.
    if common.engine.resident_max_bytes and "JANUS_RESIDENT_MAX_BYTES" not in os.environ:
        EngineCache.RESIDENT_MAX_BYTES = int(common.engine.resident_max_bytes)
    if (
        common.engine.cross_task_coalesce is not None
        and "JANUS_XTASK_COALESCE" not in os.environ
    ):
        from .aggregator import engine_cache as engine_cache_mod

        engine_cache_mod.XTASK_COALESCE = bool(common.engine.cross_task_coalesce)
    # mesh serving geometry (`engine: mesh: {dp, sp}`): pins the
    # (dp, sp) axes engines build instead of auto-selecting from the
    # device count; validated per-engine (single-device processes fall
    # back to the unsharded path regardless). JANUS_MESH_DP/SP envs win.
    if common.engine.mesh_dp is not None and "JANUS_MESH_DP" not in os.environ:
        EngineCache.MESH_DP = int(common.engine.mesh_dp)
    if common.engine.mesh_sp is not None and "JANUS_MESH_SP" not in os.environ:
        EngineCache.MESH_SP = int(common.engine.mesh_sp)
    BOOT.phase_done("backend_init")

    keys = parse_datastore_keys(args.datastore_keys)
    ds = open_datastore(common.database.url, Crypter(keys), RealClock())
    if "JANUS_SLOW_TX_WARN_S" not in os.environ:
        # the env var is the operator override; only the YAML value is
        # applied when it's absent (else it would be silently dead in
        # every binary — the class default already read it)
        ds.slow_tx_warn_s = common.database.slow_tx_warn_secs
    ds.retry_max_interval_s = common.database.retry_max_interval_secs

    # datastore connection supervision: background health probe driving
    # the up/degraded/down/recovering state machine, /statusz section
    # and the /readyz readiness split (liveness /healthz stays up — a
    # DB outage is a reason to stop routing, never to kill the process)
    if common.database.health_probe_interval_secs > 0:
        supervisor = ds.start_supervision(
            probe_interval_s=common.database.health_probe_interval_secs,
            down_threshold=common.database.down_after_failures,
            reconnect_max_interval_s=common.database.reconnect_max_interval_secs,
        )
        register_status_provider("datastore", supervisor.status)
        register_readiness_check("datastore", supervisor.readiness)

    # /statusz base sections: build/process info and the provisioned
    # tasks (subsystems — engine cache, ingest, health sampler — add
    # their own sections as they come up)
    def _process_status():
        from . import __version__

        info = {
            "version": __version__,
            "role": description,
            "pid": os.getpid(),
            "config_file": args.config_file,
            "database_url": common.database.url,
            "jax_platform": common.jax_platform or os.environ.get("JAX_PLATFORMS"),
            "health_sampler_interval_s": common.health_sampler_interval_s,
        }
        try:
            import jax

            info["jax_version"] = jax.__version__
        except Exception:
            pass
        return info

    def _tasks_status():
        from .metrics import task_id_label

        tasks = ds.run_tx(lambda tx: tx.get_tasks(), "statusz_tasks")
        return [
            {
                "task_id": task_id_label(t.task_id.data),
                "role": t.role.name,
                "vdaf": t.vdaf.kind,
                "xof_mode": t.vdaf.xof_mode,
                "query_type": t.query_type.code,
            }
            for t in tasks
        ]

    register_status_provider("process", _process_status)
    register_status_provider("tasks", _tasks_status)
    BOOT.phase_done("datastore")

    # --- persisted shape manifest + AOT prewarm (ISSUE 14; docs/
    # ARCHITECTURE.md "Cold-start and prewarm"): load the manifest of
    # observed dispatch specializations and compile the recorded set —
    # highest recorded cost first, bounded by the boot budget — BEFORE
    # the health listener is up, so /readyz never reports a replica
    # ready that would stall its first jobs on cold compiles. The
    # JANUS_SHAPE_MANIFEST env var is the operator override; an empty
    # path ("" in YAML or env) disables recording and prewarm, and a
    # manifest-less boot degrades to the legacy warmup below.
    manifest = None
    manifest_path = os.environ.get("JANUS_SHAPE_MANIFEST")
    if manifest_path is None:
        manifest_path = common.engine.shape_manifest_path
    if manifest_path is None and compile_cache_dir:
        manifest_path = os.path.join(
            os.path.expanduser(compile_cache_dir), shape_manifest_mod.DEFAULT_FILENAME
        )
    if manifest_path:
        try:
            manifest = shape_manifest_mod.install_manifest(
                manifest_path,
                max_entries=common.engine.shape_manifest_max_entries,
            )
        except Exception:
            log.exception("could not install the shape manifest at %s", manifest_path)
    BOOT.phase_done("engine_warm_manifest")

    prewarm_ready = threading.Event()
    register_readiness_check(
        "engine_prewarm",
        lambda: None
        if prewarm_ready.is_set()
        else "boot-budget engine prewarm still compiling",
    )
    prewarm_ran = False
    if common.engine.prewarm and manifest is not None:
        try:
            prewarm_mod.prewarm_engines(
                ds,
                manifest,
                boot_budget_s=common.engine.prewarm_boot_budget_secs,
                ready_event=prewarm_ready,
            )
            prewarm_ran = True
        except Exception:
            log.exception("manifest prewarm failed; serving cold")
    prewarm_ready.set()  # idempotent (prewarm_engines sets it after the
    # priority set); a disabled/failed prewarm must never wedge /readyz
    if common.warmup_engines_at_boot:
        # dedupe against the manifest ONLY when the prewarm really
        # warmed it — with prewarm disabled/failed, a covered geometry
        # would otherwise be skipped by BOTH paths and serve its first
        # job cold
        dedupe = manifest if prewarm_ran else _NO_DEDUPE
        if common.warmup_buckets:
            # non-blocking: serve immediately, compile buckets behind
            warmup_engines_background(ds, common.warmup_buckets, manifest=dedupe)
        else:
            warmup_engines(ds, manifest=dedupe)
    BOOT.phase_done("engine_warm")

    # in-process SLO burn-rate engine (YAML `slo:` stanza; ISSUE 10):
    # evaluates the burn-rate ladder over the live registry and serves
    # GET /alertz + the `slo` statusz section on the health listener
    from . import slo as slo_mod

    slo_engine = None
    if common.slo.enabled:
        slo_engine = slo_mod.install_slo_engine(common.slo)

    # always-on sampling profiler (YAML `profiler:` stanza; ISSUE 13):
    # wall-clock stacks behind GET /debug/profile on the listener below
    profiler_mod.install_profiler(common.profiler)

    # telemetry flight recorder (YAML `flight:` stanza; ISSUE 18):
    # low-cadence resource/metric history + trend/leak verdicts behind
    # GET /debug/flight, feeding the `trend` SLO signal above
    from . import flight_recorder as flight_mod

    flight_mod.install_flight_recorder(common.flight)

    stopper = Stopper()
    if install_signals:
        setup_signal_handler(stopper)
    health = HealthServer(common.health_check_listen_address).start()
    log.info("health/metrics listener on port %d", health.port)
    # the listener is up and every registered readiness check is live:
    # this is the moment /readyz starts answering — seal the boot record
    BOOT.phase_done("listener_up")
    BOOT.mark_ready()
    try:
        return run(cfg, ds, stopper)
    finally:
        health.stop()
        flight_mod.uninstall_flight_recorder()
        profiler_mod.uninstall_profiler()
        if slo_engine is not None:
            slo_mod.uninstall_slo_engine()
        # teardown ordering against interpreter finalization — a daemon
        # thread running REAL device work while the interpreter
        # finalizes crashes inside native XLA: (1) stop engine canary
        # loops (bounded join of an in-flight probe), (2) unpark
        # hang-failpoint wedges (they raise at the site), (3) let
        # abandoned watchdog workers retire
        shutdown_engines(2.0)
        failpoints.release_hangs()
        device_watchdog.WATCHDOG.drain(2.0)
        unregister_readiness_check("engine_prewarm")
        shape_manifest_mod.uninstall_manifest()
        from .aggregator import aot_cache

        aot_cache.disarm()
        ds.close()
