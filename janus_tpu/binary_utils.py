"""Binary harness shared by the five processes.

Equivalent of reference aggregator/src/binary_utils.rs: `janus_main`
(config parse -> trace subscriber -> metrics -> datastore -> run),
the /healthz listener (also serving /metrics Prometheus text), and
SIGTERM -> Stopper graceful shutdown (binary_utils.rs:40-120,
docs/DEPLOYING.md:33-39).

Datastore keys come from --datastore-keys or the DATASTORE_KEYS env
var (comma-separated base64, first key is primary), matching the
reference's k8s-secret pathway.
"""

from __future__ import annotations

import argparse
import base64
import logging
import os
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .aggregator.job_driver import Stopper
from .config import CommonConfig, load_config
from .core.time_util import RealClock
from .datastore.store import Crypter, open_datastore
from .metrics import REGISTRY
from .trace import install_trace_subscriber

log = logging.getLogger(__name__)


def parse_datastore_keys(raw: str) -> list[bytes]:
    keys = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        pad = "=" * (-len(part) % 4)
        keys.append(base64.urlsafe_b64decode(part + pad))
    if not keys:
        raise ValueError("at least one datastore key is required")
    for k in keys:
        if len(k) != 16:
            raise ValueError("datastore keys must be 16 bytes (AES-128-GCM)")
    return keys


def _split_hostport(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)


class HealthServer:
    """GET /healthz -> 200; GET /metrics -> Prometheus text
    (reference serves /healthz from binary_utils.rs and metrics via the
    OTel Prometheus exporter, metrics.rs:53-80)."""

    def __init__(self, addr: str):
        host, port = _split_hostport(addr)

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    body, ctype = b"", "text/plain"
                elif self.path == "/metrics":
                    body, ctype = REGISTRY.render().encode(), "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "HealthServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def setup_signal_handler(stopper: Stopper) -> None:
    """SIGTERM/SIGINT -> cooperative stop (binary_utils.rs
    setup_signal_handler). Only callable from the main thread."""

    def handle(signum, frame):
        log.info("received signal %s, shutting down", signum)
        stopper.stop()

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)


def janus_main(description: str, config_cls, run, argv=None, install_signals: bool = True):
    """Shared entry point (reference binary_utils.rs janus_main).

    `run(cfg, ds, stopper)` is the binary body; this harness owns config
    parsing, logging, the health/metrics listener, the datastore and
    signal handling.
    """
    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--config-file", required=True, help="YAML configuration file")
    parser.add_argument(
        "--datastore-keys",
        default=os.environ.get("DATASTORE_KEYS", ""),
        help="comma-separated base64url AES-128 keys (or DATASTORE_KEYS env)",
    )
    args = parser.parse_args(argv)

    cfg = load_config(args.config_file, config_cls)
    common: CommonConfig = cfg.common
    install_trace_subscriber(common.logging_config)

    if common.jax_platform:
        os.environ["JAX_PLATFORMS"] = common.jax_platform
        try:
            import jax

            jax.config.update("jax_platforms", common.jax_platform)
        except Exception:
            log.exception("could not pin JAX platform %r", common.jax_platform)

    keys = parse_datastore_keys(args.datastore_keys)
    ds = open_datastore(common.database.url, Crypter(keys), RealClock())

    stopper = Stopper()
    if install_signals:
        setup_signal_handler(stopper)
    health = HealthServer(common.health_check_listen_address).start()
    log.info("health/metrics listener on port %d", health.port)
    try:
        return run(cfg, ds, stopper)
    finally:
        health.stop()
        ds.close()
