"""Stage-pipelined leader stepper (ISSUE 9 tentpole).

The serial stepper runs every leased aggregation job as one chain on
one worker thread — read tx -> host staging -> device init -> helper
RTT -> device accumulate -> write tx — so the chip idles behind the
datastore and the helper round trip at exactly the batch sizes the
kernels want (the host-pipeline-starves-the-accelerator failure mode
"Enabling AI ASICs for ZKP" describes for ZKP offload). This module
restructures the step into an explicit staged pipeline:

    read    (prefetch_depth workers): read_tx + columnar staging — job
            k+1 stages while job k occupies the device
    device  (the DEVICE LANE, device_lane_workers=1 by default): EVERY
            device dispatch — leader init and the masked accumulate —
            runs here, so a dispatch is never parked behind a helper
            RTT or a commit. The lane re-enters the job's ambient
            lease-deadline scope per stage, so the PR 7 watchdog /
            quarantine semantics apply unchanged; with lane workers
            > 1, the engine's own coalescing gate merges the
            concurrent dispatches exactly as it does for concurrent
            serial steppers.
    http    (http_inflight workers): columnar request framing, the
            helper round trip, columnar response decode + host-side
            verification
    commit  (commit_inflight workers): the write tx + lease release

Jobs that are not on the prio3 init hot path (multi-round continue
steps, poplar1, empty jobs) run their existing serial step body as one
opaque "classic" stage on the http/commit executors — same code, same
semantics, no device-lane involvement (their device work, if any, is
still watchdog-supervised by the ambient deadline).

Correctness invariants:

  * a job is in EXACTLY ONE stage at a time (the chain enqueues the
    next stage only after the previous returned), so the pipeline can
    never lose or double-step a job; the write tx is byte-for-byte the
    serial stepper's;
  * the lease budget is RE-CHECKED at every stage hand-off
    (deadline.check), and the HTTP leg recomputes it at call time
    (AggregationJobDriver._send_agg_job_request_raw) — a job whose
    budget died waiting in a stage queue steps back instead of dialing;
  * any stage failure maps through the driver's handle_step_error to
    the existing step-back / attempt-ledger semantics (circuit open,
    deadline expired, device hang, datastore down), identical to the
    serial stepper;
  * SIGTERM drain: in-flight chains run to completion (JobDriver.run
    waits on the outer futures before returning); a step that fails
    during drain releases its lease immediately via the releaser, as
    the serial path does.

Observability: janus_step_pipeline_stage_seconds{stage},
janus_step_pipeline_queue_depth{stage}, janus_device_lane_busy_ratio,
janus_step_pipeline_overlap_total, a `step_pipeline` /statusz section,
and a per-job "job.step" flight-recorder digest observation (the bench
served phase reads p50/p95 from it).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

from .. import metrics
from ..core import deadline as deadline_mod
from ..datastore.models import AggregationJobState

log = logging.getLogger(__name__)

STAGE_READ = "read"
STAGE_DEVICE = "device"
STAGE_HTTP = "http"
STAGE_COMMIT = "commit"
STAGE_CLASSIC = "classic"  # metric label for non-pipelined step bodies
STAGES = (STAGE_READ, STAGE_DEVICE, STAGE_HTTP, STAGE_COMMIT)


@dataclass
class StepPipelineConfig:
    """YAML `step_pipeline:` stanza of the aggregation job driver
    (docs/samples/aggregation_job_driver.yaml)."""

    enabled: bool = True
    # jobs reading + staging ahead of the device lane (bounded: each
    # prefetched job holds its staged columns in host memory)
    prefetch_depth: int = 2
    # concurrent helper round trips (encode/send/decode/verify legs)
    http_inflight: int = 2
    # concurrent write transactions
    commit_inflight: int = 2
    # device-lane width. 1 (default) = fully serialized dispatches; >1
    # re-enables cross-job coalescing at the engine gate for small jobs
    device_lane_workers: int = 1
    # double-buffered staging (ISSUE 12): the read stage issues job
    # k+1's padded host->device column uploads ASYNC right after
    # staging, so the transfer overlaps job k's dispatch on the lane
    # instead of serializing in front of k+1's own dispatch. Staged
    # device bytes are bounded by the same prefetch_depth window as the
    # host columns.
    double_buffer: bool = True

    @classmethod
    def from_dict(cls, d: dict | None) -> "StepPipelineConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            prefetch_depth=max(1, int(d.get("prefetch_depth", 2))),
            http_inflight=max(1, int(d.get("http_inflight", 2))),
            commit_inflight=max(1, int(d.get("commit_inflight", 2))),
            device_lane_workers=max(1, int(d.get("device_lane_workers", 1))),
            double_buffer=bool(d.get("double_buffer", True)),
        )


class DeviceLane:
    """Serialized owner of device dispatches: a bounded executor whose
    busy time is accounted, so "is the chip saturated" is one gauge
    (janus_device_lane_busy_ratio, rolling window) plus a counter
    (janus_device_lane_busy_seconds_total) for rate()-based alerts.
    Tracks the concurrency high-water mark so tests can pin the
    serialization contract."""

    # rolling window for the busy-ratio gauge: the ratio reads the last
    # WINDOW..2*WINDOW seconds, never the process lifetime — an
    # overnight-idle driver must not mask a saturated morning (and vice
    # versa). Alerts wanting other widths rate() the counter instead.
    RATIO_WINDOW_S = 60.0

    def __init__(self, workers: int = 1):
        self.workers = workers
        self._pool = ThreadPoolExecutor(workers, thread_name_prefix="device-lane")
        self._lock = threading.Lock()
        t0 = time.monotonic()
        self.busy_s = 0.0
        self.dispatches = 0
        self.concurrent = 0
        self.concurrent_peak = 0
        # two-snapshot rolling window: ratio is computed against the
        # previous snapshot (age WINDOW..2*WINDOW); rolls forward every
        # WINDOW seconds
        self._prev_t, self._prev_busy = t0, 0.0
        self._snap_t, self._snap_busy = t0, 0.0
        # the gauge must DECAY while the lane is idle (dispatch-end is
        # the only other update site, so a saturated burst followed by
        # hours of idle would export ~1.0 forever): a low-cadence
        # refresher keeps the exported window honest between dispatches
        self._stop = threading.Event()
        self._refresher = threading.Thread(
            target=self._refresh_loop, name="device-lane-gauge", daemon=True
        )
        self._refresher.start()

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.RATIO_WINDOW_S / 4):
            metrics.device_lane_busy_ratio.set(self.busy_ratio())

    def submit(self, fn, *args) -> Future:
        return self._pool.submit(self._run, fn, *args)

    def _run(self, fn, *args):
        with self._lock:
            self.concurrent += 1
            self.concurrent_peak = max(self.concurrent_peak, self.concurrent)
        t0 = time.monotonic()
        try:
            return fn(*args)
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                self.concurrent -= 1
                self.busy_s += dt
                self.dispatches += 1
            metrics.device_lane_busy_seconds.add(dt)
            metrics.device_lane_busy_ratio.set(self.busy_ratio())

    def busy_ratio(self) -> float:
        now = time.monotonic()
        with self._lock:
            if now - self._snap_t >= self.RATIO_WINDOW_S:
                self._prev_t, self._prev_busy = self._snap_t, self._snap_busy
                self._snap_t, self._snap_busy = now, self.busy_s
            base_t, base_busy = self._prev_t, self._prev_busy
            busy = self.busy_s
        wall = now - base_t
        if wall <= 0:
            return 0.0
        return min(1.0, (busy - base_busy) / (wall * self.workers))

    def close(self, wait: bool = True) -> None:
        self._stop.set()
        self._pool.shutdown(wait=wait)


class _PipelinedStep:
    """One leased job moving through the stage chain."""

    __slots__ = ("acquired", "outer", "trace_context", "deadline", "state",
                 "classic", "t_submit", "error", "staging_permit")

    def __init__(self, acquired, outer: Future):
        self.acquired = acquired
        self.outer = outer
        self.trace_context = None  # persisted creator trace, set at read
        self.deadline = None  # lease budget, set at read
        self.state = None  # InitStepState for the hot path
        self.classic = None  # zero-arg step body for non-pipelined kinds
        self.t_submit = time.monotonic()
        self.error = None
        self.staging_permit = False  # holding a slot of the staging window


class StepPipeline:
    """Schedules AggregationJobDriver stage methods across bounded
    stage executors. submit(acquired) returns a Future that resolves
    when the job's step has fully completed (committed, stepped back,
    or failed-and-logged) — JobDriver treats it exactly like a serial
    _step_one future, so discovery, worker accounting and shutdown
    drain are unchanged."""

    def __init__(self, driver, cfg: StepPipelineConfig | None = None,
                 stopper=None, releaser=None):
        self.driver = driver
        self.cfg = cfg or StepPipelineConfig()
        self.stopper = stopper
        self.releaser = releaser
        self.lane = DeviceLane(self.cfg.device_lane_workers)
        self._pools = {
            STAGE_READ: ThreadPoolExecutor(
                self.cfg.prefetch_depth, thread_name_prefix="step-read"
            ),
            STAGE_HTTP: ThreadPoolExecutor(
                self.cfg.http_inflight, thread_name_prefix="step-http"
            ),
            STAGE_COMMIT: ThreadPoolExecutor(
                self.cfg.commit_inflight, thread_name_prefix="step-commit"
            ),
        }
        self._lock = threading.Lock()
        self._http_inflight = 0
        self._queued = {stage: 0 for stage in STAGES}
        self._jobs_done = 0
        # overlap accounting, split by direction so the ratio below is
        # the quantity its name claims: _overlap_device counts device
        # dispatches that STARTED while an HTTP leg was in flight (the
        # numerator of overlap_ratio); _overlap_http counts the reverse
        # interleaving (an HTTP leg starting while the lane is busy),
        # which proves overlap just as well but must not inflate the
        # per-dispatch ratio
        self._overlap_device = 0
        self._overlap_http = 0
        self._closed = False
        # the REAL staged-memory bound: at most prefetch_depth jobs may
        # hold staged columns (InitStepState arrays) that the device
        # has not consumed yet — the read pool only bounds concurrent
        # read transactions, and without this window jobs would pile up
        # staged-but-unconsumed in the device-lane queue, up to the
        # driver's whole worker count
        self._staging_window = threading.Semaphore(self.cfg.prefetch_depth)
        from ..statusz import register_status_provider

        # keep the exact registered object: bound-method accesses make
        # fresh objects, and close()'s guarded unregister is an
        # identity check
        self._status_provider = self.status
        register_status_provider("step_pipeline", self._status_provider)

    # --- submission ----------------------------------------------------
    def submit(self, acquired) -> Future:
        outer: Future = Future()
        job = _PipelinedStep(acquired, outer)
        self._enqueue(STAGE_READ, self._stage_read, job)
        return outer

    def _enqueue(self, stage: str, fn, job: _PipelinedStep, label: str | None = None) -> None:
        with self._lock:
            self._queued[stage] += 1
            metrics.step_pipeline_queue_depth.set(self._queued[stage], stage=stage)
        try:
            if stage == STAGE_DEVICE:
                self.lane.submit(self._run_stage, stage, fn, job, label)
            else:
                self._pools[stage].submit(self._run_stage, stage, fn, job, label)
        except RuntimeError as e:
            # pool shut down mid-chain (close() raced a straggler):
            # surface instead of silently stranding the lease
            with self._lock:
                self._queued[stage] -= 1
                metrics.step_pipeline_queue_depth.set(self._queued[stage], stage=stage)
            self._fail(job, e)

    # --- stage execution -----------------------------------------------
    def _run_stage(self, stage: str, fn, job: _PipelinedStep, label: str | None) -> None:
        from ..trace import use_traceparent

        # only the REAL helper-RTT stage counts as an in-flight HTTP
        # leg for the overlap proof: a "classic" step body on the HTTP
        # pool (continue/poplar1) mixes RTTs with staging and write
        # txs, and counting it would inflate the overlap metric
        is_http = stage == STAGE_HTTP and label is None
        with self._lock:
            self._queued[stage] -= 1
            metrics.step_pipeline_queue_depth.set(self._queued[stage], stage=stage)
            direction = None
            if is_http:
                self._http_inflight += 1
                if self.lane.concurrent > 0:
                    direction = "http_start"
                    self._overlap_http += 1
            elif stage == STAGE_DEVICE and self._http_inflight > 0:
                direction = "device_start"
                self._overlap_device += 1
            if direction is not None:
                # the overlap proof: a device dispatch and a helper RTT
                # are in flight at the same instant — the serial stepper
                # could never be in both at once
                metrics.step_pipeline_overlap_total.add(direction=direction)
        t0 = time.monotonic()
        err: BaseException | None = None
        nxt = None
        try:
            # re-enter the job's trace + lease-budget scopes on THIS
            # stage thread (contextvars do not cross threads), then
            # re-check the budget before doing any stage work: a job
            # whose lease died in the queue steps back here
            with use_traceparent(job.trace_context), deadline_mod.deadline_scope(
                job.deadline
            ):
                deadline_mod.check(f"step_pipeline_{stage}")
                nxt = fn(job)
        except BaseException as e:  # noqa: BLE001 — mapped to step-back below
            err = e
        finally:
            # drop the in-flight mark BEFORE enqueueing the next stage,
            # or a chain's own just-finished HTTP leg would count as
            # overlapping its device_accumulate
            if is_http:
                with self._lock:
                    self._http_inflight -= 1
        self._observe_stage(label or stage, time.monotonic() - t0)
        if err is not None:
            if stage == STAGE_DEVICE:
                # never run the step-back transaction on the device
                # lane: a DeviceHangError with a slow/down datastore
                # would park every queued dispatch (which host fallback
                # could still serve) behind DB I/O
                try:
                    self._pools[STAGE_COMMIT].submit(self._fail, job, err)
                    return
                except RuntimeError:
                    pass  # commit pool already shut down: handle inline
            self._fail(job, err)
        elif nxt is None:
            self._finish(job)
        else:
            nstage, nfn, nlabel = nxt if len(nxt) == 3 else (*nxt, None)
            self._enqueue(nstage, nfn, job, nlabel)

    def _observe_stage(self, stage: str, dur_s: float) -> None:
        metrics.step_pipeline_stage_seconds.observe(dur_s, stage=stage)

    def _finish(self, job: _PipelinedStep) -> None:
        from ..trace import record_operation

        self._release_staging(job)  # no-op unless the chain died staged
        with self._lock:
            self._jobs_done += 1
        args = {"job": type(job.acquired).__name__, "pipelined": True}
        if job.error is not None:
            args["error"] = job.error
        record_operation("job.step", time.monotonic() - job.t_submit, **args)
        job.outer.set_result(None)

    def _fail(self, job: _PipelinedStep, e: BaseException) -> None:
        """Map a stage failure to the serial stepper's semantics
        (AggregationJobDriver.stepper + JobDriver._step_one)."""
        job.error = type(e).__name__
        try:
            if isinstance(e, Exception) and self.driver.handle_step_error(
                job.acquired, e
            ):
                self._finish(job)
                return
        except Exception:
            log.exception(
                "step-back handling itself failed for job %s", job.acquired.job_id
            )
            self._finish(job)
            return
        if (
            self.stopper is not None
            and self.stopper.stopped
            and self.releaser is not None
        ):
            # shutdown drain: this process will not retry — release the
            # lease now so a surviving peer picks the job up immediately
            log.error(
                "pipelined job step failed during shutdown; releasing lease",
                exc_info=e,
            )
            try:
                self.releaser(job.acquired)
            except Exception:
                log.exception("shutdown lease release failed")
        else:
            log.error(
                "pipelined job %s step failed (attempt %d; lease will expire and retry)",
                job.acquired.job_id,
                job.acquired.lease.attempts,
                exc_info=e,
            )
        self._finish(job)

    # --- the stage bodies ----------------------------------------------
    def _stage_read(self, job: _PipelinedStep):
        driver = self.driver
        acquired = job.acquired
        if acquired.lease.attempts > driver.cfg.maximum_attempts_before_failure:
            driver.abandon_job(acquired)
            return None
        task, jobrow, ras, reports = driver.read_job(acquired)
        if jobrow is None or task is None:
            raise RuntimeError("job or task vanished while leased")
        if jobrow.state != AggregationJobState.IN_PROGRESS:
            driver.release_job(acquired)
            return None
        # adopt the persisted creator trace + the lease budget for every
        # later stage (and for the rest of THIS one: staging below runs
        # under the scopes, like the serial stepper's _step_leased_job)
        job.trace_context = jobrow.trace_context
        job.deadline = driver._lease_deadline(acquired)

        from ..trace import use_traceparent

        with use_traceparent(job.trace_context), deadline_mod.deadline_scope(
            job.deadline
        ):
            kind, rows = driver.plan_step(acquired, task, jobrow, ras)
            if kind == "continue":
                job.classic = lambda: driver._continue_step(acquired, task, jobrow, rows)
                return (STAGE_HTTP, self._stage_classic, STAGE_CLASSIC)
            if kind == "poplar1":
                job.classic = lambda: driver._step_poplar1_init(
                    acquired, task, jobrow, rows, reports
                )
                return (STAGE_HTTP, self._stage_classic, STAGE_CLASSIC)
            if kind == "empty":
                job.classic = lambda: driver.finish_empty(acquired, jobrow)
                return (STAGE_COMMIT, self._stage_classic, STAGE_CLASSIC)
            # blocks this read worker while prefetch_depth jobs already
            # hold unconsumed staged columns — the staged-memory bound
            self._staging_window.acquire()
            job.staging_permit = True
            st = driver.stage_init(acquired, task, jobrow, rows, reports)
            job.state = st
            if self.cfg.double_buffer:
                # double-buffered staging: issue THIS job's padded H2D
                # transfers async now, on the read thread — they overlap
                # whatever dispatch currently occupies the device lane,
                # and device_init consumes them without a host put
                prestage = getattr(st.engine, "prestage_leader", None)
                would_coalesce = getattr(st.engine, "would_coalesce", None)
                if (
                    prestage is not None
                    and self.cfg.device_lane_workers > 1
                    and would_coalesce is not None
                    and would_coalesce(st.nonce_lanes.shape[0])
                ):
                    # a parallel device lane means coalesced rounds can
                    # MERGE, and a merged round discards its entries'
                    # prestages (it re-stages from concatenated host
                    # columns) — don't pay the H2D transfer twice for
                    # exactly the small jobs coalescing targets
                    prestage = None
                if prestage is not None:
                    try:
                        st.prestaged = prestage(
                            st.nonce_lanes, st.public_parts, st.meas,
                            st.proof, st.blind_lanes,
                        )
                    except Exception:
                        log.warning(
                            "prestage failed for job %s; device_init will "
                            "stage from host",
                            acquired.job_id,
                            exc_info=True,
                        )
                        st.prestaged = None
            return (STAGE_DEVICE, self._stage_device_init)

    def _release_staging(self, job: _PipelinedStep) -> None:
        if job.staging_permit:
            job.staging_permit = False
            self._staging_window.release()

    def _stage_classic(self, job: _PipelinedStep):
        job.classic()
        return None

    def _stage_device_init(self, job: _PipelinedStep):
        try:
            self.driver.device_init(job.state)
        finally:
            # the device consumed the staged columns (leader_init's H2D
            # transfers complete before it returns): free the host
            # arrays — and any unconsumed prestaged device buffers —
            # and open the staging window for the next prefetch
            st = job.state
            st.meas = st.proof = st.blind_lanes = st.public_parts = None
            st.nonce_lanes = None
            if st.prestaged is not None:
                st.prestaged.discard()
                st.prestaged = None
            self._release_staging(job)
        return (STAGE_HTTP, self._stage_http_init)

    def _stage_http_init(self, job: _PipelinedStep):
        self.driver.http_init(job.state)
        if job.state.multi_round:
            return (STAGE_COMMIT, self._stage_commit_park)
        return (STAGE_DEVICE, self._stage_device_accumulate)

    def _stage_device_accumulate(self, job: _PipelinedStep):
        self.driver.device_accumulate(job.state)
        return (STAGE_COMMIT, self._stage_commit_finish)

    def _stage_commit_park(self, job: _PipelinedStep):
        self.driver.commit_park(job.state)
        return None

    def _stage_commit_finish(self, job: _PipelinedStep):
        self.driver.commit_finish(job.state)
        return None

    # --- lifecycle / introspection --------------------------------------
    def status(self) -> dict:
        with self._lock:
            queued = dict(self._queued)
            jobs_done = self._jobs_done
            overlap_device = self._overlap_device
            overlap_http = self._overlap_http
            http_inflight = self._http_inflight
        lane = self.lane
        return {
            "jobs_done": jobs_done,
            "queued": queued,
            "http_inflight": http_inflight,
            "device_lane": {
                "workers": lane.workers,
                "dispatches": lane.dispatches,
                "busy_s": round(lane.busy_s, 3),
                "busy_ratio": round(lane.busy_ratio(), 4),
                "concurrent_peak": lane.concurrent_peak,
            },
            # overlap_ratio is exactly what its name claims: the
            # fraction of device dispatches that STARTED while an HTTP
            # leg was in flight. overlap_events additionally counts the
            # reverse interleaving — either direction nonzero proves
            # the pipeline is overlapping
            "overlapped_dispatches": overlap_device,
            "overlap_events": overlap_device + overlap_http,
            "overlap_ratio": min(1.0, round(overlap_device / lane.dispatches, 4))
            if lane.dispatches
            else 0.0,
            "config": {
                "prefetch_depth": self.cfg.prefetch_depth,
                "http_inflight": self.cfg.http_inflight,
                "commit_inflight": self.cfg.commit_inflight,
                "device_lane_workers": self.cfg.device_lane_workers,
            },
        }

    def close(self, wait: bool = True) -> None:
        """Shut the stage executors down. Callers must first drain
        in-flight chains (JobDriver.run waits on the outer futures
        before returning), so this only retires idle workers."""
        if self._closed:
            return
        self._closed = True
        from ..statusz import unregister_status_provider

        # guarded: a newer pipeline's registration must survive
        unregister_status_provider("step_pipeline", self._status_provider)
        for pool in self._pools.values():
            pool.shutdown(wait=wait)
        self.lane.close(wait=wait)
