"""AOT engine prewarm from the persisted shape manifest (ISSUE 14;
docs/ARCHITECTURE.md "Cold-start and prewarm").

`prewarm_engines` replays the shape manifest (shape_manifest.py)
against the provisioned tasks at boot: every recorded dispatch
specialization — (vdaf, op, bucket, jit variant) — is re-dispatched
with synthetic data of exactly that geometry, so the trace happens and
the persistent XLA compile cache is loaded BEFORE /readyz reports
ready. Entries are warmed highest-recorded-cost first and bounded by a
boot budget; the remainder continues on a background thread (role
`engine_warm` in the profiler taxonomy), so one pathological manifest
can delay readiness by at most the budget, never forever.

The same warmer serves the quarantine canary (engine_cache._canary_loop):
a restored engine's dropped executables are re-warmed from the
manifest in the canary thread, so restore means restored-to-full-speed,
not restored-to-recompile-per-dispatch.

Observability: `janus_engine_prewarm_total{outcome}` +
`janus_engine_prewarm_seconds` and the /statusz `engine_prewarm`
section (compile cache dir + file counts, manifest inventory, hit/miss
split — a "hit" is a warm whose compile landed without growing the
cache dir, i.e. a persistent-cache load).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from ..statusz import register_status_provider
from . import shape_manifest

log = logging.getLogger(__name__)

DEFAULT_BOOT_BUDGET_S = 30.0

# module state behind the /statusz `engine_prewarm` section; always
# well-formed, even in a process that never prewarms
_state_lock = threading.Lock()
_STATE: dict = {
    "state": "idle",  # idle | running | ready | done | disabled
    "warmed": 0,
    "cache_hits": 0,
    "cache_misses": 0,
    "failed": 0,
    "unsupported": 0,
    "no_task": 0,
    "deferred": 0,
    "geometry_mismatch": 0,
    "boot_budget_s": None,
    "priority_elapsed_s": None,
}
_COMPILE_CACHE: dict = {"enabled": False, "dir": None}


def note_compile_cache(cache_dir: str | None) -> None:
    """Record the live persistent-compile-cache directory for the
    statusz section (binary_utils.enable_compile_cache calls this)."""
    with _state_lock:
        _COMPILE_CACHE["enabled"] = cache_dir is not None
        _COMPILE_CACHE["dir"] = cache_dir


def _cache_dir_stats() -> tuple[int, int]:
    """(files, bytes) in the compile cache dir (0, 0 when unknown)."""
    d = _COMPILE_CACHE.get("dir")
    if not d:
        return 0, 0
    files = total = 0
    try:
        with os.scandir(os.path.expanduser(d)) as it:
            for ent in it:
                try:
                    if ent.is_file():
                        files += 1
                        total += ent.stat().st_size
                except OSError:
                    continue
    except OSError:
        return 0, 0
    return files, total


def _bump(outcome: str, n: int = 1) -> None:
    from .. import metrics

    metrics.engine_prewarm_total.add(n, outcome=outcome)
    with _state_lock:
        if outcome in (
            "warmed",
            "failed",
            "unsupported",
            "no_task",
            "deferred",
            "geometry_mismatch",
        ):
            key = outcome
            _STATE[key] = _STATE.get(key, 0) + n


def engine_prewarm_status() -> dict:
    """The /statusz `engine_prewarm` section: compile cache state,
    manifest inventory and the prewarm outcome counts."""
    files, nbytes = _cache_dir_stats()
    with _state_lock:
        state = dict(_STATE)
        cache = dict(_COMPILE_CACHE)
    cache["files"] = files
    cache["bytes"] = nbytes
    from . import aot_cache

    return {
        "compile_cache": cache,
        "aot": aot_cache.status(),
        "manifest": shape_manifest.manifest_status(),
        "prewarm": state,
    }


register_status_provider("engine_prewarm", engine_prewarm_status)


def reset_for_tests() -> None:
    with _state_lock:
        _STATE.update(
            state="idle",
            warmed=0,
            cache_hits=0,
            cache_misses=0,
            failed=0,
            unsupported=0,
            no_task=0,
            deferred=0,
            boot_budget_s=None,
            priority_elapsed_s=None,
        )


# ---------------------------------------------------------------------------
# Warming one recorded specialization. The warmer re-dispatches through
# the ENGINE's own entry points (never raw jax.jit), so the compiled
# program is byte-for-byte the one serving traffic will use — warm
# results are bit-identical to cold ones by construction, and the
# dispatch feeds the same cost ledger / manifest choke points.
# ---------------------------------------------------------------------------


def _tile_rows(a, n: int):
    """Broadcast a 1-row staged arg (array / field-limb tuple / None /
    bytes) to n rows along the leading (report) axis."""
    import numpy as np

    if a is None or isinstance(a, (bytes, int)):
        return a
    if isinstance(a, tuple):
        return tuple(_tile_rows(x, n) for x in a)
    a = np.asarray(a)
    return np.repeat(a, n, axis=0)


class _Warmer:
    """Per-run context: generates ONE synthetic report per engine and
    TILES it to each target row count. Compiled programs depend only on
    shapes, never values, so a duplicated row is as good as n distinct
    reports — and it skips the per-report host share generation that
    would otherwise dominate a warm boot (measured: the difference
    between a ~30 s and a <10 s warm restart at 20 manifest entries).
    Leader-init outputs are cached per (engine, rows) so helper/
    aggregate entries reuse the leader leg instead of re-dispatching
    it."""

    def __init__(self):
        self._base: dict[int, tuple] = {}
        self._batches: dict[tuple, tuple] = {}

    def _rows_for_bucket(self, bucket: int) -> int:
        # smallest n whose jit bucket is `bucket` — minimal staged
        # bytes for the same compiled program
        return bucket // 2 + 1

    def _batch(self, eng, n: int):
        import numpy as np

        from ..vdaf.testing import make_report_batch, random_measurements

        key = (id(eng), n)
        got = self._batches.get(key)
        if got is None:
            base = self._base.get(id(eng))
            if base is None:
                rng = np.random.default_rng(0xC01D)
                base, _ = make_report_batch(
                    eng.inst, random_measurements(eng.inst, 1, rng), seed=0xC01D
                )
                self._base[id(eng)] = base
            args = tuple(_tile_rows(a, n) for a in base)
            got = self._batches[key] = (args, {})
        return got

    def _leader_out(self, eng, n: int):
        """leader_init outputs at rows n (cached per engine+n)."""
        args, cache = self._batch(eng, n)
        if "leader" not in cache:
            nonce, parts, meas, proof, blind0, _, _ = args
            cache["leader"] = eng._leader_init_inner(
                nonce, parts, meas, proof, blind0, allow_pipeline=False
            )
        return args, cache["leader"]

    def warm(self, eng, entry: dict) -> str:
        """Warm one manifest entry on `eng`; returns the outcome."""
        import numpy as np

        from .engine_cache import MIN_BUCKET, DeviceRows, HostEngineCache, bucket_size

        if isinstance(eng, HostEngineCache) or eng._host() is not None:
            return "unsupported"  # nothing to compile on the host path
        key = [str(k) if not isinstance(k, (int, float)) else k for k in entry.get("key") or ()]
        # a specialization recorded under a different mesh topology is
        # a DIFFERENT program: replaying it here would trace something
        # serving never dispatches and burn the boot budget on it
        # (e.g. a single-device boot reading a (dp=4, sp=1) manifest,
        # or a pod reading a laptop's) — skip, distinctly counted
        from .shape_manifest import entry_geometry

        recorded = entry_geometry(key)
        current = (
            (eng.dp, eng.sp, eng._ndev) if eng.mesh is not None else None
        )
        if recorded != current:
            return "geometry_mismatch"
        variant = str(key[0]) if key else str(entry.get("op", ""))
        bucket = int(entry.get("bucket", 0))
        if bucket < max(MIN_BUCKET, eng.dp) or (
            eng.bucket_cap is not None and bucket > eng.bucket_cap
        ):
            return "unsupported"
        n = self._rows_for_bucket(bucket)
        vk_lanes = None
        if variant.endswith("_vk"):
            vk_lanes = np.ascontiguousarray(
                np.broadcast_to(
                    np.frombuffer(eng.verify_key, dtype="<u8").astype(np.uint64),
                    (n, 2),
                )
            )
        if variant in ("leader_init", "leader_init_vk"):
            args, _ = self._batch(eng, n)
            nonce, parts, meas, proof, blind0, _, _ = args
            eng._leader_init_inner(
                nonce, parts, meas, proof, blind0,
                allow_pipeline=False, vk_lanes=vk_lanes,
            )
            return "warmed"
        if variant in ("helper_init", "helper_init_vk"):
            args, (out0, seed0, ver0, part0) = self._leader_out(eng, n)
            nonce, parts, _, _, _, hseed, blind1 = args
            ok = np.ones(n, dtype=bool)
            part0_l = (
                part0 if part0 is not None else np.zeros((n, 2), dtype=np.uint64)
            )
            eng._helper_init_inner(
                nonce, parts, hseed, blind1, ver0, part0_l, ok, vk_lanes=vk_lanes
            )
            return "warmed"
        if variant == "aggregate":
            _, (out0, _, _, _) = self._leader_out(eng, n)
            eng.aggregate(out0, np.ones(n, dtype=bool))
            return "warmed"
        if variant.startswith("aggregate_view_"):
            try:
                vb = int(variant.rsplit("_", 1)[1])
            except ValueError:
                return "unsupported"
            if vb < MIN_BUCKET or bucket_size(vb) != vb:
                return "unsupported"
            # a view needs a buffer WIDER than its own bucket: stage a
            # leader init at 2*vb rows, aggregate a vb-row view of it
            n2 = self._rows_for_bucket(2 * vb)
            _, (out_big, _, _, _) = self._leader_out(eng, n2)
            if not isinstance(out_big, DeviceRows):
                return "unsupported"
            view = DeviceRows(out_big.value, min(vb, out_big.n), offset=0)
            eng.aggregate(view, np.ones(view.n, dtype=bool))
            return "warmed"
        if variant == "aggregate_pending":
            kk = int(key[1]) if len(key) > 1 else 1
            _, (out0, _, _, _) = self._leader_out(eng, n)
            idx = (np.arange(n, dtype=np.int32) % max(1, kk)).astype(np.int32)
            eng.aggregate_pending(out0, idx, max(1, kk))
            return "warmed"
        if variant == "scatter_merge":
            # block-sparse scatter-add into a dense logical accumulator
            # (ISSUE 17): the program specializes on (row bucket,
            # compact_len, logical_len) — replay with every compact
            # lane live, which traces the same shapes serving uses
            if not getattr(eng, "sparse", False):
                return "unsupported"
            cm = eng.p3.circ.output_len
            _, (out0, _, _, _) = self._leader_out(eng, n)
            flat = np.tile(np.arange(cm, dtype=np.int32), (n, 1))
            eng.aggregate_sparse(out0, np.ones(n, dtype=bool), flat)
            return "warmed"
        return "unsupported"


def _vdaf_key(d: dict) -> str:
    return json.dumps(dict(d), sort_keys=True, separators=(",", ":"))


def _warm_one(warmer: _Warmer, eng, entry: dict) -> str:
    from .. import metrics
    from . import aot_cache

    aot0 = aot_cache.stats()  # O(1) counters, no directory scan
    t0 = time.monotonic()
    try:
        outcome = warmer.warm(eng, entry)
    except Exception:
        log.warning(
            "prewarm of %s failed", entry.get("key"), exc_info=True
        )
        outcome = "failed"
    elapsed = time.monotonic() - t0
    if outcome == "warmed":
        metrics.engine_prewarm_seconds.observe(elapsed)
        # hit/miss: an AOT executable load is the canonical warm hit,
        # an AOT save the canonical cold miss; without AOT activity
        # (disarmed, or a specialization already live in _jits) call a
        # sub-second warm a hit and anything slower a miss — the only
        # signal left once neither cache moved
        aot1 = aot_cache.stats()
        with _state_lock:
            if aot1["loads"] > aot0["loads"]:
                _STATE["cache_hits"] += 1
            elif aot1["saves"] > aot0["saves"] or elapsed >= 1.0:
                _STATE["cache_misses"] += 1
            else:
                _STATE["cache_hits"] += 1
    _bump(outcome)
    return outcome


def prewarm_engines(
    ds,
    manifest: "shape_manifest.ShapeManifest | None" = None,
    boot_budget_s: float = DEFAULT_BOOT_BUDGET_S,
    ready_event: "threading.Event | None" = None,
    background_remainder: bool = True,
) -> dict:
    """Replay the shape manifest against the provisioned tasks.

    Warms entries highest-recorded-cost first until `boot_budget_s` of
    wall time is spent; the remainder (counted `deferred`) continues on
    a daemon thread so readiness is never hostage to a long tail.
    Returns a summary dict (also reflected in the /statusz
    `engine_prewarm` section). `ready_event`, when given, is set the
    moment the priority (in-budget) set is warm — the `engine_prewarm`
    readiness check keys off it."""
    from .engine_cache import engine_cache

    manifest = manifest if manifest is not None else shape_manifest.installed()
    t0 = time.monotonic()
    entries = manifest.entries() if manifest is not None else []
    summary = {"entries": len(entries), "warmed": 0, "deferred": 0}
    with _state_lock:
        _STATE["state"] = "running" if entries else "done"
        _STATE["boot_budget_s"] = boot_budget_s
    if not entries:
        if ready_event is not None:
            ready_event.set()
        with _state_lock:
            _STATE["priority_elapsed_s"] = 0.0
        summary["priority_elapsed_s"] = 0.0
        return summary

    tasks = ds.run_tx(lambda tx: tx.get_tasks(), "prewarm_list_tasks")
    by_vdaf: dict[str, list] = {}
    for task in tasks:
        if task.vdaf.kind.startswith("fake") or task.vdaf.kind == "poplar1":
            continue
        by_vdaf.setdefault(_vdaf_key(task.vdaf.to_dict()), []).append(task)

    jobs: list[tuple[dict, object]] = []
    for entry in entries:
        matched = by_vdaf.get(_vdaf_key(entry.get("vdaf") or {}))
        if not matched:
            _bump("no_task")
            continue
        for task in matched:
            jobs.append((entry, task))

    warmer = _Warmer()
    remainder: list[tuple[dict, object]] = []
    deferred_oversize: list[tuple[dict, object]] = []
    for i, (entry, task) in enumerate(jobs):
        elapsed = time.monotonic() - t0
        if elapsed > boot_budget_s:
            remainder = jobs[i:]
            break
        # an entry whose RECORDED cold cost alone dwarfs the whole
        # budget defers immediately: a compile cannot be preempted, so
        # starting it would hold readiness far past the documented
        # bound (worst case it is a cheap cache hit we warm a little
        # later in background; worst case avoided is a 170 s compile
        # behind a 30 s budget). Budget overshoot is otherwise bounded
        # by ONE specialization's warm time.
        if float(entry.get("cost_s", 0.0)) > 2.0 * boot_budget_s:
            deferred_oversize.append((entry, task))
            continue
        eng = engine_cache(task.vdaf, task.vdaf_verify_key)
        if _warm_one(warmer, eng, entry) == "warmed":
            summary["warmed"] += 1
    remainder = deferred_oversize + remainder
    elapsed = time.monotonic() - t0
    summary["priority_elapsed_s"] = round(elapsed, 3)
    summary["deferred"] = len(remainder)
    with _state_lock:
        _STATE["state"] = "ready"
        _STATE["priority_elapsed_s"] = round(elapsed, 3)
    if ready_event is not None:
        ready_event.set()
    if remainder:
        _bump("deferred", len(remainder))
        log.info(
            "engine prewarm: %d specialization(s) warmed in %.1fs; %d deferred "
            "past the %.1fs boot budget to the background warmer",
            summary["warmed"], elapsed, len(remainder), boot_budget_s,
        )
        if background_remainder:

            def _drain():
                w = _Warmer()
                for entry, task in remainder:
                    try:
                        eng = engine_cache(task.vdaf, task.vdaf_verify_key)
                        _warm_one(w, eng, entry)
                    except Exception:
                        log.warning("background prewarm failed", exc_info=True)
                with _state_lock:
                    _STATE["state"] = "done"

            threading.Thread(
                target=_drain, name="engine-warmup-bg", daemon=True
            ).start()
    else:
        with _state_lock:
            _STATE["state"] = "done"
        log.info(
            "engine prewarm: %d specialization(s) warmed in %.1fs (budget %.1fs)",
            summary["warmed"], elapsed, boot_budget_s,
        )
    return summary


def warm_engine_from_manifest(eng, budget_s: float = 60.0, should_stop=None) -> int:
    """Re-warm ONE engine's recorded specializations (the quarantine
    canary's restore path: `_canary_probe` dropped the compiled
    executables, so without this every post-restore dispatch pays a
    re-trace — from-disk-cheap with the persistent cache, but still
    worth doing off the serving path). `should_stop` is checked
    between entries so process teardown can end the loop — a daemon
    thread dispatching native device work while the interpreter
    finalizes crashes the runtime (the stop_canary hazard). Returns
    the warmed count."""
    manifest = shape_manifest.installed()
    if manifest is None:
        return 0
    want = _vdaf_key(eng.inst.to_dict())
    warmer = _Warmer()
    warmed = 0
    t0 = time.monotonic()
    for entry in manifest.entries():
        if should_stop is not None and should_stop():
            break
        if _vdaf_key(entry.get("vdaf") or {}) != want:
            continue
        if time.monotonic() - t0 > budget_s:
            break
        if _warm_one(warmer, eng, entry) == "warmed":
            warmed += 1
    return warmed
