"""Aggregator error taxonomy -> DAP problem details.

Equivalent of reference aggregator/src/aggregator/error.rs +
problem_details.rs: typed errors that map to (HTTP status, problem
type) pairs at the HTTP boundary.
"""

from __future__ import annotations

from ..messages.problem_type import DapProblemType


class AggregatorError(Exception):
    status = 500
    problem: DapProblemType | None = None

    def __init__(self, detail: str = "", task_id=None):
        super().__init__(detail)
        self.detail = detail
        self.task_id = task_id

    def problem_document(self) -> dict | None:
        if self.problem is None:
            return None
        tid = None
        if self.task_id is not None:
            import base64

            tid = base64.urlsafe_b64encode(self.task_id.data).decode().rstrip("=")
        return self.problem.document(task_id=tid, detail=self.detail or None)


class UnrecognizedTask(AggregatorError):
    status = 400
    problem = DapProblemType.UNRECOGNIZED_TASK


class UnrecognizedAggregationJob(AggregatorError):
    status = 400
    problem = DapProblemType.UNRECOGNIZED_AGGREGATION_JOB


class UnrecognizedCollectionJob(AggregatorError):
    status = 400
    problem = DapProblemType.UNRECOGNIZED_COLLECTION_JOB


class UnauthorizedRequest(AggregatorError):
    status = 400
    problem = DapProblemType.UNAUTHORIZED_REQUEST


class InvalidMessage(AggregatorError):
    status = 400
    problem = DapProblemType.INVALID_MESSAGE


class OutdatedHpkeConfig(AggregatorError):
    status = 400
    problem = DapProblemType.OUTDATED_CONFIG


class ReportRejected(AggregatorError):
    status = 400
    problem = DapProblemType.REPORT_REJECTED


class ReportTooEarly(AggregatorError):
    status = 400
    problem = DapProblemType.REPORT_TOO_EARLY


class BatchInvalid(AggregatorError):
    status = 400
    problem = DapProblemType.BATCH_INVALID


class InvalidBatchSize(AggregatorError):
    status = 400
    problem = DapProblemType.INVALID_BATCH_SIZE


class BatchQueryCountExceeded(AggregatorError):
    status = 400
    problem = DapProblemType.BATCH_QUERY_COUNT_EXCEEDED


class BatchMismatch(AggregatorError):
    status = 400
    problem = DapProblemType.BATCH_MISMATCH


class BatchOverlap(AggregatorError):
    status = 400
    problem = DapProblemType.BATCH_OVERLAP


class StepMismatch(AggregatorError):
    status = 400
    problem = DapProblemType.STEP_MISMATCH


class InvalidTask(AggregatorError):
    """taskprov opt-out (reference error.rs InvalidTask/OptOutReason)."""

    status = 400
    problem = DapProblemType.INVALID_TASK
