"""Expired-artifact garbage collection.

Equivalent of reference aggregator/src/aggregator/garbage_collector.rs:9-75:
per task, delete expired client reports, aggregation artifacts and
collection artifacts in one transaction each, bounded per pass by row
limits. Expiry cutoffs come from the task's report_expiry_age; tasks
without one are skipped (nothing ever expires).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from ..datastore.store import Datastore

log = logging.getLogger(__name__)


@dataclass
class GarbageCollectorConfig:
    """reference garbage_collector.rs limits."""

    report_limit: int = 5000
    aggregation_limit: int = 10000
    collection_limit: int = 50


class GarbageCollector:
    def __init__(self, ds: Datastore, clock, cfg: GarbageCollectorConfig | None = None):
        self.ds = ds
        self.clock = clock
        self.cfg = cfg or GarbageCollectorConfig()

    def run_once(self) -> dict[str, int]:
        """One GC pass over every task; returns rows deleted by kind."""
        totals = {"reports": 0, "aggregation": 0, "collection": 0}
        tasks = self.ds.run_tx(lambda tx: tx.get_tasks(), "gc_list_tasks")
        for task in tasks:
            if task.report_expiry_age is None:
                continue
            deleted = self.gc_task(task)
            for k, v in deleted.items():
                totals[k] += v
        return totals

    def gc_task(self, task) -> dict[str, int]:
        cutoff = self.clock.now().sub(task.report_expiry_age)
        cfg = self.cfg

        def tx_fn(tx):
            return {
                "reports": tx.delete_expired_client_reports(
                    task.task_id, cutoff, cfg.report_limit
                ),
                "aggregation": tx.delete_expired_aggregation_artifacts(
                    task.task_id, cutoff, cfg.aggregation_limit
                ),
                "collection": tx.delete_expired_collection_artifacts(
                    task.task_id, cutoff, cfg.collection_limit
                ),
            }

        deleted = self.ds.run_tx(tx_fn, "gc_task")
        if any(deleted.values()):
            log.info("gc task %s: deleted %s", task.task_id, deleted)
        return deleted
