"""Expired-artifact garbage collection.

Equivalent of reference aggregator/src/aggregator/garbage_collector.rs:9-75:
per task, delete expired client reports, aggregation artifacts and
collection artifacts in one transaction each, bounded per pass by row
limits. Expiry cutoffs come from the task's report_expiry_age; tasks
without one are skipped (nothing ever expires).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from .. import metrics
from ..datastore.store import Datastore

log = logging.getLogger(__name__)


@dataclass
class GarbageCollectorConfig:
    """reference garbage_collector.rs limits."""

    report_limit: int = 5000
    aggregation_limit: int = 10000
    collection_limit: int = 50


class GarbageCollector:
    def __init__(self, ds: Datastore, clock, cfg: GarbageCollectorConfig | None = None):
        self.ds = ds
        self.clock = clock
        self.cfg = cfg or GarbageCollectorConfig()
        self._last_pass_unix: float | None = None
        metrics.gc_lag_seconds.set(-1.0)

    def run_once(self) -> dict[str, int]:
        """One GC pass over every task; returns rows deleted by kind.
        Progress is exported for the flight recorder's endurance gates:
        janus_gc_deleted_rows_total{kind} and janus_gc_tasks_scanned_
        total rise with the work, janus_gc_lag_seconds tracks the age
        of the last COMPLETED pass (a growing lag with GC configured on
        means passes are stuck or erroring)."""
        totals = {"reports": 0, "aggregation": 0, "collection": 0}
        try:
            tasks = self.ds.run_tx(lambda tx: tx.get_tasks(), "gc_list_tasks")
            for task in tasks:
                if task.report_expiry_age is None:
                    continue
                metrics.gc_tasks_scanned_total.add()
                deleted = self.gc_task(task)
                for k, v in deleted.items():
                    totals[k] += v
        except Exception:
            metrics.gc_runs_total.add(outcome="error")
            if self._last_pass_unix is not None:
                metrics.gc_lag_seconds.set(time.time() - self._last_pass_unix)
            raise
        for k, v in totals.items():
            if v:
                metrics.gc_deleted_rows_total.add(v, kind=k)
        metrics.gc_runs_total.add(outcome="ok")
        self._last_pass_unix = time.time()
        metrics.gc_lag_seconds.set(0.0)
        return totals

    def observe_lag(self) -> float:
        """Refresh + return janus_gc_lag_seconds (the health sampler
        calls this between passes so the gauge moves even when the GC
        loop is wedged and never reaches run_once's own update)."""
        if self._last_pass_unix is None:
            return -1.0
        lag = time.time() - self._last_pass_unix
        metrics.gc_lag_seconds.set(lag)
        return lag

    def gc_task(self, task) -> dict[str, int]:
        cutoff = self.clock.now().sub(task.report_expiry_age)
        cfg = self.cfg

        def tx_fn(tx):
            unclaimed, claimed = tx.delete_expired_client_reports(
                task.task_id, cutoff, cfg.report_limit
            )
            jobs, pending_ras, pending_param_ras = tx.delete_expired_aggregation_artifacts(
                task.task_id, cutoff, cfg.aggregation_limit
            )
            collection = tx.delete_expired_collection_artifacts(
                task.task_id, cutoff, cfg.collection_limit
            )
            # conservation ledger attribution, in the SAME tx as the
            # deletes (exactly-once under run_tx retries): an expired
            # never-claimed report leaves the pending pool for the
            # `expired` terminal, and so does a claimed report whose
            # report_aggregations row died non-terminal with its
            # expired job (abandoned jobs' released START rows excluded
            # — their reports resolve through the unclaimed pool).
            # Param-fanout rows book their own lane (`expired_param`):
            # they debited `admitted_param`, never `admitted`. Claimed
            # rows whose RA already resolved were booked aggregated/
            # rejected at resolution — deleting their storage is not a
            # lifecycle event, only `expired_reclaimed` bookkeeping for
            # /debug/ledger.
            tx.increment_task_counters(
                task.task_id,
                {
                    "expired": unclaimed + pending_ras,
                    "expired_param": pending_param_ras,
                    "expired_reclaimed": claimed,
                },
            )
            return {
                "reports": unclaimed + claimed,
                "aggregation": jobs,
                "collection": collection,
            }

        deleted = self.ds.run_tx(tx_fn, "gc_task")
        if any(deleted.values()):
            log.info("gc task %s: deleted %s", task.task_id, deleted)
        return deleted
