"""Periodic datastore health sampler: the serving-side SLO gauges.

A production DAP deployment operates against aggregation lag — how far
behind the oldest unaggregated report is, how deep the job backlog
runs, how long leases stay outstanding (Prio-class systems alert on
exactly these; the reference surfaces them via its aggregator-api task
metrics and OTel instruments). This sampler runs cheap read-only
datastore queries on a period (CommonConfig.health_sampler_interval_s)
and exports:

  janus_jobs{type,state}                          job backlog (gauge)
  janus_job_lease_age_seconds                     max outstanding lease age
  janus_oldest_unaggregated_report_age_seconds{task_id}
  janus_unaggregated_report_age_seconds{task_id,quantile}
                                                  freshness p50/p95/p99
  janus_batches_pending_collection                collection jobs pending

plus a /statusz section with the latest snapshot. The companion
counter janus_task_reports_aggregated_total is NOT sampled — the
accumulator increments it at accumulate time (accumulator.py).

Lease age caveat: the schema stores only lease_expiry, not the acquire
time, so age is measured from when THIS sampler first observed the
lease — a lower bound on the true age (exact once the lease has been
visible for one sampling period).
"""

from __future__ import annotations

import logging
import os
import threading

from ..metrics import task_id_label as _b64_task_id

log = logging.getLogger(__name__)


def _path_bytes(path: str) -> int:
    """On-disk bytes of a file, or the recursive total of a directory
    (one level of nesting is enough for the journal/AOT blob dirs).
    Missing paths are 0 — an artifact that was never created is empty,
    not an error."""
    path = os.path.expanduser(path)
    try:
        if os.path.isdir(path):
            total = 0
            for root, _dirs, files in os.walk(path):
                for name in files:
                    try:
                        total += os.path.getsize(os.path.join(root, name))
                    except OSError:
                        pass
            return total
        return os.path.getsize(path)
    except OSError:
        return 0


def artifact_paths_from_config(common, aggregator=None) -> dict[str, str]:
    """{artifact label: path} for janus_artifact_bytes, derived from a
    CommonConfig (+ optionally the AggregatorConfig for the upload
    journal): the spill journal dir, the shape manifest and the AOT
    blob dir — the locally persisted state that can leak bytes."""
    out = {}
    if aggregator is not None and getattr(aggregator, "upload_journal_path", None):
        out["upload_journal"] = aggregator.upload_journal_path
    cache_dir = common.engine.compile_cache_dir or common.compilation_cache_dir
    manifest = common.engine.shape_manifest_path
    if manifest is None and cache_dir:
        manifest = os.path.join(cache_dir, "shape_manifest.jsonl")
    if manifest:
        out["shape_manifest"] = manifest
    if cache_dir and common.engine.aot_cache:
        out["aot_cache"] = os.path.join(cache_dir, "aot")
    return out


class HealthSampler:
    """Thread-per-process sampler over one datastore. `run_once()` is
    the unit of work (tests and the bench smoke call it directly);
    `start()` spawns the periodic daemon thread.

    `artifact_paths` ({label: path}, see artifact_paths_from_config)
    adds on-disk artifact size sampling (janus_artifact_bytes);
    `gc` (a GarbageCollector) adds janus_gc_lag_seconds refreshes
    between GC passes. Both feed the flight recorder's leak-gated
    series; the table row counts (janus_datastore_table_rows) are
    always sampled."""

    def __init__(
        self, ds, interval_s: float = 15.0, artifact_paths=None, gc=None, ledger=None
    ):
        self.ds = ds
        self.artifact_paths = dict(artifact_paths or {})
        self.gc = gc
        # conservation-ledger evaluator (janus_tpu/ledger.py): balance
        # evaluation rides the sampler cadence so "the books close
        # within one sampler interval" is literally one run_once
        self.ledger = ledger
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # (type, task_id, job_id) -> clock seconds at first observation
        self._lease_first_seen: dict[tuple, int] = {}
        # task_id labels we exported last pass (stale ones reset to 0)
        self._lag_tasks: set[str] = set()
        self._quantile_tasks: set[str] = set()
        self.last_snapshot: dict = {}
        from ..statusz import register_status_provider

        register_status_provider("job_health", lambda: self.last_snapshot)

    # ------------------------------------------------------------------
    def run_once(self) -> dict:
        from .. import metrics
        from ..datastore.models import AggregationJobState, CollectionJobState

        now = self.ds.clock.now().seconds
        # per-replica labels (docs/ARCHITECTURE.md "Running a fleet"):
        # {} in single-process deployments, {"replica": id} when a
        # fleet identity is configured — N samplers exporting the same
        # backlog gauges to one scrape plane stay distinguishable
        rl = metrics.replica_labels()

        jobs = self.ds.run_tx(lambda tx: tx.count_jobs_by_state(), "health_jobs_by_state")
        # zero-fill the known states so a drained backlog decays to 0
        # instead of freezing at its last nonzero sample
        for state in AggregationJobState:
            jobs.setdefault(("aggregation", state.value), 0)
        for state in CollectionJobState:
            jobs.setdefault(("collection", state.value), 0)
        for (typ, state), count in sorted(jobs.items()):
            metrics.jobs_gauge.set(float(count), type=typ, state=state, **rl)

        leases = self.ds.run_tx(
            lambda tx: tx.get_held_lease_expiries(), "health_held_leases"
        )
        live_keys = set()
        max_age = 0
        for typ, task_id, job_id, _expiry in leases:
            key = (typ, bytes(task_id), bytes(job_id))
            live_keys.add(key)
            first = self._lease_first_seen.setdefault(key, now)
            max_age = max(max_age, now - first)
        # drop released/expired leases so a re-acquired job starts fresh
        for key in list(self._lease_first_seen):
            if key not in live_keys:
                del self._lease_first_seen[key]
        metrics.job_lease_age_seconds.set(float(max_age), **rl)

        # one scan feeds BOTH the oldest-age gauge (exact min) and the
        # freshness DISTRIBUTION — per-task p50/p95/p99 unaggregated
        # ages (a single stuck report and a systemically lagging task
        # look identical on the min alone)
        quants = self.ds.run_tx(
            lambda tx: tx.unaggregated_report_time_quantiles_by_task(),
            "health_freshness_quantiles",
        )
        seen_tasks = set()
        lag_by_task = {}
        freshness = {}
        for task_id, count, min_time, vals in quants:
            label = _b64_task_id(bytes(task_id))
            seen_tasks.add(label)
            age = float(max(0, now - min_time))
            lag_by_task[label] = age
            metrics.oldest_unaggregated_report_age_seconds.set(age, task_id=label, **rl)
            per_task = {"count": count}
            for q, t in vals.items():
                qlabel = f"p{round(q * 100):d}"
                qage = float(max(0, now - t))
                per_task[qlabel] = qage
                metrics.unaggregated_report_age_quantiles.set(
                    qage, task_id=label, quantile=qlabel, **rl
                )
            freshness[label] = per_task
        for label in self._lag_tasks - seen_tasks:
            metrics.oldest_unaggregated_report_age_seconds.set(0.0, task_id=label, **rl)
        for label in self._quantile_tasks - seen_tasks:
            for qlabel in ("p50", "p95", "p99"):
                metrics.unaggregated_report_age_quantiles.set(
                    0.0, task_id=label, quantile=qlabel, **rl
                )
        self._lag_tasks = seen_tasks
        self._quantile_tasks = seen_tasks

        pending = self.ds.run_tx(
            lambda tx: tx.count_batches_pending_collection(), "health_batches_pending"
        )
        metrics.batches_pending_collection.set(float(pending), **rl)

        # long-horizon state the flight recorder trends: per-table row
        # counts (flat under load + GC is the endurance gate), on-disk
        # artifact bytes, and a GC-lag refresh between GC passes
        table_rows = self.ds.run_tx(
            lambda tx: tx.count_table_rows(), "health_table_rows"
        )
        for table, count in sorted(table_rows.items()):
            metrics.datastore_table_rows.set(float(count), table=table, **rl)
        artifact_bytes = {}
        for label, path in sorted(self.artifact_paths.items()):
            size = _path_bytes(path)
            artifact_bytes[label] = size
            metrics.artifact_bytes.set(float(size), artifact=label, **rl)
        if self.gc is not None:
            self.gc.observe_lag()
        if self.ledger is not None:
            # evaluate_once never raises (errors keep the previous
            # balance document and count as outcome="error")
            self.ledger.evaluate_once()

        self.last_snapshot = {
            "sampled_at_clock_seconds": now,
            "jobs": {f"{typ}/{state}": n for (typ, state), n in sorted(jobs.items())},
            "outstanding_leases": len(leases),
            "max_lease_age_seconds": max_age,
            "oldest_unaggregated_report_age_seconds": lag_by_task,
            "unaggregated_report_age_quantiles": freshness,
            "batches_pending_collection": pending,
            "datastore_table_rows": table_rows,
            "artifact_bytes": artifact_bytes,
            "interval_s": self.interval_s,
        }
        return self.last_snapshot

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        # first pass immediately: a scrape right after boot (exactly
        # when ops check a restarted aggregator) must not see an empty
        # job_health section for a whole interval
        while True:
            try:
                self.run_once()
            except Exception:
                # sampling must never take the process down, and a
                # transiently unreachable database just skips a sample
                log.exception("health sampling pass failed")
            if self._stop.wait(self.interval_s):
                return

    def start(self) -> "HealthSampler":
        self._thread = threading.Thread(
            target=self._loop, name="health-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
