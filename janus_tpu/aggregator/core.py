"""Aggregator protocol handlers (request-scoped brain).

Equivalent of reference aggregator/src/aggregator.rs:156-3033
(`Aggregator`, `TaskAggregator`, `VdafOps`): hpke_config, upload,
aggregate_init (helper), aggregate_continue, collection-job CRUD,
aggregate_share — with the per-report loops of the reference replaced
by columnar device batches (engine_cache) and lane masks.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass, field

from .. import failpoints, ledger, metrics
from ..core import deadline as deadline_mod
from ..core.hpke import HpkeApplicationInfo, HpkeError, Label, hpke_open, hpke_seal
from ..core.time_util import Clock, RealClock
from ..datastore.models import (
    AggregateShareJob,
    AggregationJobModel,
    AggregationJobState,
    ReportAggregationModel,
    ReportAggregationState,
)
from ..datastore.store import Datastore
from ..messages import (
    AggregateShare,
    AggregateShareAad,
    AggregateShareReq,
    AggregationJobId,
    AggregationJobInitializeReq,
    AggregationJobResp,
    BatchSelector,
    Collection,
    CollectionJobId,
    CollectionReq,
    Duration,
    HpkeCiphertext,
    HpkeConfigId,
    HpkeConfigList,
    InputShareAad,
    Interval,
    PartialBatchSelector,
    PlaintextInputShare,
    PrepareError,
    PrepareResp,
    PrepareStepResult,
    Report,
    ReportId,
    ReportIdChecksum,
    Role,
    TaskId,
    Time,
    TimeInterval,
)
from ..messages.codec import DecodeError
from ..datastore.models import CollectionJobModel, CollectionJobState
from ..task import Task
from ..vdaf.registry import circuit_for
from ..vdaf.wire import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    Prio3Wire,
    decode_index_columns,
    decode_pingpong,
    encode_field_rows,
    encode_pingpong,
    flat_scatter_indices,
    lanes_to_seed_rows,
    seeds_to_lanes,
    split_prep_share_columns,
)

# Round-1 helper prep share carried in the two-round fake VDAF's
# ping-pong CONTINUE (opaque bytes; the fake's round-2 check is a
# prep-message echo — the *machinery* is what multi-round exercises).
FAKE_ROUND1_PREP_SHARE = b"fake-round1-ps!!"


def _err_or_default(err) -> "PrepareError":
    """PrepareError.BATCH_COLLECTED has enum value 0 (falsy), so the
    `err or DEFAULT` idiom silently rewrites it; compare against None."""
    return err if err is not None else PrepareError.VDAF_PREP_ERROR
from . import errors
from .accumulator import (
    Accumulator,
    accumulate_batched,
    add_encoded_aggregate_shares,
    fixed_size_batch_id,
)
from .engine_cache import engine_cache

import numpy as np


@dataclass
class Config:
    """reference aggregator.rs:186-218."""

    max_upload_batch_size: int = 100
    # 0 = pure group commit (the reference's default write delay,
    # aggregator.rs:186-218); >0 adds a coalescing window
    max_upload_batch_write_delay_ms: int = 0
    batch_aggregation_shard_count: int = 1
    taskprov_enabled: bool = False
    # Retry-After (seconds) on 202 collection-job polls; the collector
    # honors it (reference collector/src/lib.rs:466)
    collection_retry_after_s: int = 1
    # --- ingest pipeline + admission control (docs/INGEST.md) ---
    # HPKE-decrypt pool size; 0 = sized from the crypto backend's
    # batch GIL-release capability (cores when the batch open releases
    # the GIL, 2 on the GIL-holding libcrypto fallback — see
    # ingest.pipeline.default_decrypt_workers)
    ingest_decrypt_workers: int = 0
    ingest_decode_workers: int = 1
    # flush-window batching of the decode + decrypt stages (ISSUE 11;
    # docs/INGEST.md "Batched decrypt"): max reports per window and the
    # linger a decode worker waits for the window to fill. window 1 =
    # the per-report oracle path.
    ingest_batch_window: int = 32
    ingest_batch_linger_ms: float = 2.0
    # Bound on uploads in flight through the pipeline (admission's
    # queue-depth signal and the hard queue-full backstop). Every
    # in-flight upload also parks one handler thread on its ticket, so
    # this must stay BELOW max_handler_threads for queue-pressure
    # shedding to ever fire (and to leave handler slots for the other
    # routes); a bound above it is unreachable dead config.
    ingest_queue_depth: int = 24
    # token buckets per route class; rate 0 = unlimited
    upload_bucket_rate: float = 0.0
    upload_bucket_burst: int = 0
    aggregate_bucket_rate: float = 0.0
    aggregate_bucket_burst: int = 0
    # shed order under queue pressure (first sheds first): client
    # uploads before the aggregator-to-aggregator steps that finish
    # work the system already paid for
    shed_priority: tuple = ("upload", "aggregate")
    # pipeline occupancy fraction at which shed_priority[0] sheds
    queue_high_watermark: float = 0.75
    # Retry-After for queue-pressure sheds (rate sheds advertise the
    # bucket's actual refill time)
    upload_shed_retry_after_s: float = 1.0
    # cap on concurrent HTTP handler threads in DapServer
    max_handler_threads: int = 32
    # --- durable upload spill journal (docs/ROBUSTNESS.md "Datastore
    # outages"): directory for the CRC-framed fsync-on-ack journal the
    # report writer spills to when the datastore is unreachable. None
    # (default) disarms it — the upload flush path is unchanged. ---
    upload_journal_path: str | None = None
    upload_journal_max_segment_bytes: int = 8 << 20
    upload_journal_max_total_bytes: int = 256 << 20
    upload_journal_max_segments: int = 1024
    upload_journal_spill_latency_s: float = 0.0
    upload_journal_replay_interval_s: float = 1.0
    upload_journal_full_retry_after_s: float = 30.0


class TaskAggregator:
    """Per-task protocol ops (reference aggregator.rs:797)."""

    def __init__(self, task: Task, cfg: Config, global_hpke_keypairs=None):
        self.task = task
        self.cfg = cfg
        if task.vdaf.kind == "poplar1":
            from .poplar1_ops import Poplar1Ops

            self.circ = None
            self.wire = None
            self.engine = None
            self.poplar = Poplar1Ops(task.vdaf.bits, task.vdaf_verify_key)
        else:
            self.circ = circuit_for(task.vdaf)
            self.wire = Prio3Wire(self.circ)
            self.engine = engine_cache(task.vdaf, task.vdaf_verify_key)
            self.poplar = None
        self.global_hpke_keypairs = global_hpke_keypairs

    def _hpke_keypair(self, config_id):
        """Task keypair, falling back to global keys (reference
        aggregator.rs:1676 global-key fallback; required for taskprov
        tasks, which carry no per-task HPKE keys)."""
        kp = self.task.hpke_keypair(config_id)
        if kp is None and self.global_hpke_keypairs is not None:
            kp = self.global_hpke_keypairs.keypair(config_id)
        return kp

    # ------------------------------------------------------------------
    # hpke config
    # ------------------------------------------------------------------
    def hpke_config_list(self) -> HpkeConfigList:
        return HpkeConfigList(tuple(kp.config for kp in self.task.hpke_keys))

    # ------------------------------------------------------------------
    # upload (reference aggregator.rs:1325)
    # ------------------------------------------------------------------
    def upload_prepare(self, clock: Clock, report: Report):
        """Cheap per-report checks ahead of the decrypt stage (the
        ingest pipeline's decode stage runs this): clock skew / expiry
        (reference :1344-1385), public-share well-formedness, HPKE
        keypair lookup. Returns the keypair for upload_decrypt_validate.
        """
        task = self.task
        now = clock.now()
        if report.metadata.time > now.add(task.tolerable_clock_skew):
            raise errors.ReportTooEarly("report from the future", task.task_id)
        if task.task_expiration and report.metadata.time > task.task_expiration:
            raise errors.ReportRejected("task expired", task.task_id)
        if task.report_expired(report.metadata.time, now):
            raise errors.ReportRejected("report expired", task.task_id)
        # (poplar1 public-share validation happens with the input-share
        # validation below — validate_shares decodes it once)
        if self.poplar is None:
            try:
                self.wire.decode_public_share(report.public_share)
            except DecodeError as e:
                metrics.upload_decode_failure_counter.add()
                raise errors.InvalidMessage(f"bad public share: {e}", task.task_id)

        keypair = self._hpke_keypair(report.leader_encrypted_input_share.config_id)
        if keypair is None:
            raise errors.OutdatedHpkeConfig("unknown HPKE config id", task.task_id)
        return keypair

    def upload_decrypt_validate(self, report: Report, keypair):
        """CPU-heavy upload stage (the ingest pipeline's decrypt pool
        runs this off the handler thread): decrypt + decode the leader
        input share at upload time (reference :1391) and validate it
        columnarly. Returns the LeaderStoredReport to commit."""
        from ..trace import span

        task = self.task
        aad = InputShareAad(task.task_id, report.metadata, report.public_share).to_bytes()
        try:
            with span("upload.hpke_validate"):
                plaintext = hpke_open(
                    keypair,
                    HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER),
                    report.leader_encrypted_input_share,
                    aad,
                )
                payload = PlaintextInputShare.from_bytes(plaintext).payload
                if self.poplar is not None:
                    self.poplar.validate_shares(report.public_share, payload, party=0)
                else:
                    # columnar validation, not scalar decode: the full
                    # Python decode was the measured upload bottleneck
                    # (BASELINE.md served table)
                    self.wire.validate_leader_share(payload)
        except (HpkeError, DecodeError, ValueError) as e:
            metrics.upload_decrypt_failure_counter.add()
            raise errors.ReportRejected(f"undecryptable/undecodable share: {e}", task.task_id)

        from ..datastore.models import LeaderStoredReport

        return LeaderStoredReport(
            task.task_id,
            report.metadata.report_id,
            report.metadata.time,
            report.public_share,
            payload,
            report.helper_encrypted_input_share,
        )

    # ------------------------------------------------------------------
    # batched upload stages (ISSUE 11; docs/INGEST.md "Batched decrypt").
    # Column forms of upload_prepare / upload_decrypt_validate over a
    # decoded ReportColumn window: same checks, same error types, same
    # metrics, applied per lane — the per-report methods above stay the
    # verification oracle (equivalence fuzz-pinned by
    # tests/test_ingest_batch.py) and the single-report fallback.
    # ------------------------------------------------------------------
    def upload_prepare_columns(self, clock: Clock, col, idxs) -> list:
        """upload_prepare over lanes `idxs` of a ReportColumn. Returns
        a list aligned with idxs: the lane's HPKE keypair when
        admitted, else the error instance upload_prepare would have
        raised for that report."""
        task = self.task
        now = clock.now()
        max_time = now.add(task.tolerable_clock_skew).seconds
        expiry = task.task_expiration.seconds if task.task_expiration else None
        kp_cache: dict[int, object] = {}
        # sparse tasks: the index predicate over the whole window in one
        # vectorized pass (reject-divergence vs the per-report reference
        # decoder is fuzz-pinned by tests/test_sparse_vdaf.py); a lane
        # with a wrong total length gets None -> ok=False, matching the
        # reference decoder's length check
        sparse_ok = None
        if self.poplar is None and self.wire.sparse:
            rows = [
                col.public_shares[i]
                if len(col.public_shares[i]) == self.wire.public_share_len
                else None
                for i in idxs
            ]
            _, sparse_ok = decode_index_columns(rows, self.wire.circ)
        out: list = []
        for k, i in enumerate(idxs):
            t = col.times[i]
            if t > max_time:
                out.append(errors.ReportTooEarly("report from the future", task.task_id))
                continue
            if expiry is not None and t > expiry:
                out.append(errors.ReportRejected("task expired", task.task_id))
                continue
            if task.report_expired(Time(t), now):
                out.append(errors.ReportRejected("report expired", task.task_id))
                continue
            if self.poplar is None:
                if sparse_ok is not None:
                    if not sparse_ok[k]:
                        metrics.upload_decode_failure_counter.add()
                        out.append(
                            errors.InvalidMessage(
                                "bad public share: invalid sparse block indices",
                                task.task_id,
                            )
                        )
                        continue
                else:
                    try:
                        self.wire.decode_public_share(col.public_shares[i])
                    except DecodeError as e:
                        metrics.upload_decode_failure_counter.add()
                        out.append(
                            errors.InvalidMessage(f"bad public share: {e}", task.task_id)
                        )
                        continue
            cfg = col.leader_config_ids[i]
            if cfg not in kp_cache:
                kp_cache[cfg] = self._hpke_keypair(HpkeConfigId(cfg))
            keypair = kp_cache[cfg]
            if keypair is None:
                out.append(
                    errors.OutdatedHpkeConfig("unknown HPKE config id", task.task_id)
                )
                continue
            out.append(keypair)
        return out

    def upload_decrypt_validate_batch(self, col, idxs, keypair) -> list:
        """upload_decrypt_validate over lanes `idxs` of a ReportColumn,
        all carrying `keypair`'s config id (the pipeline groups lanes
        by config id before calling). One hpke_open_batch spans the
        window, the leader-share range validation collapses into one
        numpy pass, and each lane comes back as its LeaderStoredReport
        or the error instance the per-report oracle would have raised."""
        import struct as _struct

        from ..core.hpke import hpke_open_batch
        from ..datastore.models import LeaderStoredReport
        from ..messages import plaintext_input_share_payload_fast
        from ..trace import span

        task = self.task
        tid = task.task_id.data
        n = len(idxs)
        # raw InputShareAad build: task_id || report_id || time ||
        # u32-length-prefixed public share (== InputShareAad.to_bytes)
        aads = [
            tid
            + col.report_ids[i]
            + _struct.pack(">QI", col.times[i], len(col.public_shares[i]))
            + col.public_shares[i]
            for i in idxs
        ]
        with span("upload.hpke_validate_batch", batch=n):
            metrics.hpke_batch_size.observe(n)
            opened = hpke_open_batch(
                keypair,
                HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER),
                [col.leader_encs[i] for i in idxs],
                [col.leader_payloads[i] for i in idxs],
                aads,
            )

            def reject(e) -> errors.ReportRejected:
                metrics.upload_decrypt_failure_counter.add()
                return errors.ReportRejected(
                    f"undecryptable/undecodable share: {e}", task.task_id
                )

            out: list = [None] * n
            payloads: list = [None] * n
            for j in range(n):
                if isinstance(opened[j], HpkeError):
                    out[j] = reject(opened[j])
                    continue
                try:
                    payloads[j] = plaintext_input_share_payload_fast(opened[j])
                except DecodeError as e:
                    out[j] = reject(e)

            if self.poplar is not None:
                for j, i in enumerate(idxs):
                    if out[j] is not None:
                        continue
                    try:
                        self.poplar.validate_shares(
                            col.public_shares[i], payloads[j], party=0
                        )
                    except (DecodeError, ValueError) as e:
                        out[j] = reject(e)
            else:
                # columnar range validation, one numpy pass for the
                # whole window (validate_leader_share semantics:
                # length + field range over the meas||proof prefix)
                want_len = self.wire.leader_share_len
                nb = (self.circ.input_len + self.circ.proof_len) * self.wire.enc_size
                live: list[int] = []
                rows: list[bytes] = []
                for j in range(n):
                    if out[j] is not None:
                        continue
                    if len(payloads[j]) != want_len:
                        out[j] = reject(DecodeError("bad leader share length"))
                        continue
                    live.append(j)
                    rows.append(payloads[j][:nb])
                if live:
                    from ..vdaf.wire import lanes_in_range

                    limbs = self.wire.enc_size // 8
                    mat = np.frombuffer(b"".join(rows), dtype="<u8").reshape(
                        len(live), -1
                    )
                    ok = lanes_in_range(mat, self.circ.FIELD.MODULUS, limbs).all(
                        axis=-1
                    )
                    for k, j in enumerate(live):
                        if not ok[k]:
                            out[j] = reject(
                                DecodeError("leader share element out of field range")
                            )

            for j, i in enumerate(idxs):
                if out[j] is not None:
                    continue
                out[j] = LeaderStoredReport(
                    task.task_id,
                    ReportId(col.report_ids[i]),
                    Time(col.times[i]),
                    col.public_shares[i],
                    payloads[j],
                    col.helper_ciphertext(i),
                )
        return out

    def handle_upload(self, ds: Datastore, clock: Clock, report: Report, writer=None) -> None:
        """Single-threaded upload path (tests, tools; the serving HTTP
        layer goes through janus_tpu.ingest.IngestPipeline, which runs
        the same two stages on its own workers). `writer`: a
        ReportWriteBatcher; falls back to a direct single-report
        transaction when absent."""
        from ..trace import span

        keypair = self.upload_prepare(clock, report)
        stored = self.upload_decrypt_validate(report, keypair)
        with span("upload.write"):
            if writer is not None:
                fresh = writer.write_report(stored)  # batched tx (report_writer.rs)
            else:
                fresh = ds.run_tx(lambda tx: tx.put_client_report(stored), "upload")
        if not fresh:
            # Replay is silent success: client retries are a normal
            # at-least-once-HTTP occurrence, not an error (DAP-07
            # upload semantics; the reference's upload dedup drops the
            # duplicate row and answers 201).
            metrics.upload_replay_counter.add()

    # ------------------------------------------------------------------
    # helper aggregate init (reference aggregator.rs:1561)
    # ------------------------------------------------------------------
    def handle_aggregate_init(
        self,
        ds: Datastore,
        clock: Clock,
        job_id: AggregationJobId,
        req: AggregationJobInitializeReq,
        request_bytes: bytes,
    ) -> AggregationJobResp:
        task = self.task
        # helper-outage injection: an unhandled FailpointError here is a
        # 500 to the leader driver over real HTTP — the chaos harness's
        # "helper 5xx storm" (docs/ROBUSTNESS.md); the breaker counts it
        failpoints.hit("helper.aggregate")
        request_hash = hashlib.sha256(request_bytes).digest()

        # idempotent replay (reference :1585,1884,1526)
        existing = ds.run_tx(
            lambda tx: tx.get_aggregation_job(task.task_id, job_id), "agg_init_check"
        )
        if existing is not None:
            if existing.last_request_hash == request_hash:
                return self._replay_aggregate_init_response(ds, job_id, existing)
            raise errors.InvalidMessage("aggregation job id reuse", task.task_id)

        if req.partial_batch_selector.query_type != task.query_type.code:
            # reference rejects PBS/task query-type mismatch as invalidMessage
            raise errors.InvalidMessage(
                "partial batch selector query type mismatch", task.task_id
            )

        if self.poplar is not None:
            return self._handle_aggregate_init_poplar1(
                ds, clock, job_id, req, request_hash
            )

        inits = list(req.prepare_inits)
        n = len(inits)
        ids = [pi.report_share.metadata.report_id for pi in inits]
        if len(set(ids)) != n:  # dup report ids (reference :1590)
            raise errors.InvalidMessage("duplicate report id in init request", task.task_id)

        now = clock.now()
        prep_err = [None] * n  # per-report PrepareError or None

        from ..trace import span

        # host-side staging: HPKE open + decode columns (the per-report
        # failure modes become mask lanes; reference :1633-1768). The
        # HPKE opens run WINDOW-BATCHED through the same surface as the
        # upload path (ISSUE 11): lanes grouped by config id share one
        # EVP key/derive context and one cipher context per group.
        from ..core.hpke import hpke_open_batch
        from ..messages import plaintext_input_share_payload_fast

        helper_seed_rows: list[bytes | None] = [None] * n
        blind_rows: list[bytes | None] = [None] * n
        part_rows0: list[bytes | None] = [None] * n  # public part 0
        part_rows1: list[bytes | None] = [None] * n
        leader_prep_rows: list[bytes | None] = [None] * n
        # block-sparse tasks: validated PUBLIC block indices per lane
        idx_rows: list | None = [None] * n if self.wire.sparse else None
        with span("helper.hpke_stage", batch=n):
            # pass 1: cheap per-report checks + keypair lookup; HPKE
            # lanes collect per config id for the batched opens
            kp_cache: dict = {}
            hpke_groups: dict = {}  # config id -> (keypair, [i], encs, pays, aads)
            for i, pi in enumerate(inits):
                rs = pi.report_share
                md = rs.metadata
                if task.task_expiration and md.time > task.task_expiration:
                    prep_err[i] = PrepareError.TASK_EXPIRED
                    continue
                if task.report_expired(md.time, now):
                    prep_err[i] = PrepareError.REPORT_DROPPED
                    continue
                cfg_id = rs.encrypted_input_share.config_id
                if cfg_id not in kp_cache:
                    kp_cache[cfg_id] = self._hpke_keypair(cfg_id)
                keypair = kp_cache[cfg_id]
                if keypair is None:
                    prep_err[i] = PrepareError.HPKE_UNKNOWN_CONFIG_ID
                    continue
                group = hpke_groups.setdefault(cfg_id, (keypair, [], [], [], []))
                group[1].append(i)
                group[2].append(rs.encrypted_input_share.encapsulated_key)
                group[3].append(rs.encrypted_input_share.payload)
                group[4].append(
                    InputShareAad(task.task_id, md, rs.public_share).to_bytes()
                )

            # pass 2: one batched open per config-id group. The
            # propagated-deadline check moved from per-report to
            # per-group: the batch amortizes the decrypt to ~tens of µs
            # per report, so the check granularity a dead leader waits
            # for is one window, not one report
            plaintexts: list[bytes | None] = [None] * n
            info = HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER)
            for keypair, idxs_g, encs_g, pays_g, aads_g in hpke_groups.values():
                deadline_mod.check("helper_decrypt")
                metrics.hpke_batch_size.observe(len(idxs_g))
                opened = hpke_open_batch(keypair, info, encs_g, pays_g, aads_g)
                for i, pt in zip(idxs_g, opened):
                    if isinstance(pt, HpkeError):
                        prep_err[i] = PrepareError.HPKE_DECRYPT_ERROR
                    else:
                        plaintexts[i] = pt

            # pass 3: per-report payload/message decode into columns
            for i, pi in enumerate(inits):
                if prep_err[i] is not None or plaintexts[i] is None:
                    continue
                rs = pi.report_share
                try:
                    payload = plaintext_input_share_payload_fast(plaintexts[i])
                    seed, blind = self.wire.decode_helper_share(payload)
                    parts = self.wire.decode_public_share(rs.public_share)
                    tag, _, prep_share = decode_pingpong(pi.message)
                    if tag != PP_INITIALIZE or prep_share is None:
                        raise DecodeError("expected ping-pong initialize")
                except DecodeError:
                    prep_err[i] = PrepareError.INVALID_MESSAGE
                    continue
                helper_seed_rows[i] = seed
                blind_rows[i] = blind
                if self.wire.uses_jr:
                    part_rows0[i] = parts[0]
                    part_rows1[i] = parts[1]
                if idx_rows is not None:
                    idx_rows[i] = parts.indices
                leader_prep_rows[i] = prep_share

        # replay check against prior aggregations (reference replay
        # semantics) — one set-valued query for the whole batch, not a
        # per-report query loop
        deadline_mod.check("helper_replay_tx")
        fresh_ids = [rid for i, rid in enumerate(ids) if prep_err[i] is None]
        with span("helper.replay_tx", batch=len(fresh_ids)):
            replayed_ids = ds.run_tx(
                lambda tx: tx.get_aggregated_report_ids(task.task_id, fresh_ids),
                "agg_init_replay",
            )
        for i, rid in enumerate(ids):
            if prep_err[i] is None and rid.data in replayed_ids:
                prep_err[i] = PrepareError.REPORT_REPLAYED

        # test-only fake VDAF failure injection (the reference's
        # dummy_vdaf prep_init_fn hook, core/src/test_util/dummy_vdaf.rs:46)
        if task.vdaf.fails_at("init"):
            for i in range(n):
                if prep_err[i] is None:
                    prep_err[i] = PrepareError.VDAF_PREP_ERROR

        # columnar staging -> device
        with span("helper.columnar", batch=n):
            nonce_lanes, ok_nonce = seeds_to_lanes([rid.data for rid in ids])
            seed_lanes, ok_seed = seeds_to_lanes(helper_seed_rows)
            ver0, part0_lanes, ok_prep = split_prep_share_columns(
                self.wire, self.engine.p3.jf, leader_prep_rows
            )
            ver0 = tuple(np.asarray(x) for x in ver0)
            ok = ok_nonce & ok_seed & ok_prep & np.array([e is None for e in prep_err])
            if self.wire.uses_jr:
                blind_lanes, ok_b = seeds_to_lanes(blind_rows)
                p0_pub, ok_p0 = seeds_to_lanes(part_rows0)
                p1_pub, ok_p1 = seeds_to_lanes(part_rows1)
                ok = ok & ok_b & ok_p0 & ok_p1
                public_parts = np.stack([p0_pub, p1_pub], axis=1)
            else:
                blind_lanes = None
                public_parts = None

        out1, accept, prep_msg_lanes = self.engine.helper_init(
            nonce_lanes, public_parts, seed_lanes, blind_lanes, ver0, part0_lanes, ok
        )
        accept = accept & ok
        prep_msg_rows = lanes_to_seed_rows(prep_msg_lanes) if self.wire.uses_jr else [b""] * n

        # test-only fake failure at the step/finish stage (the reference's
        # dummy_vdaf prep_step_fn hook, core/src/test_util/dummy_vdaf.rs:57)
        if task.vdaf.fails_at("step"):
            accept = np.zeros_like(accept)

        # mark VDAF-rejected lanes
        for i in range(n):
            if prep_err[i] is None and not accept[i]:
                prep_err[i] = PrepareError.VDAF_PREP_ERROR

        for e in prep_err:
            if e is not None:
                metrics.aggregate_step_failure_counter.add(type=e.name.lower())
        # build response + rows. Multi-round VDAFs park accepted reports
        # in WaitingHelper with (prep_msg || out_share) and answer
        # ping-pong CONTINUE; the continue request finishes them
        # (reference aggregation_job_continue.rs:30-300).
        multi_round = task.vdaf.rounds > 1
        out1_rows = encode_field_rows(self.engine.p3.jf, out1) if multi_round else None
        resps = []
        report_aggs = []
        for i, pi in enumerate(inits):
            md = pi.report_share.metadata
            if prep_err[i] is None:
                if multi_round:
                    result = PrepareStepResult.cont(
                        encode_pingpong(PP_CONTINUE, prep_msg_rows[i], FAKE_ROUND1_PREP_SHARE)
                    )
                    state = ReportAggregationState.WAITING_HELPER
                    blob = prep_msg_rows[i] + out1_rows[i]
                else:
                    result = PrepareStepResult.cont(
                        encode_pingpong(PP_FINISH, prep_msg_rows[i], None)
                    )
                    state = ReportAggregationState.FINISHED
                    blob = prep_msg_rows[i]
                err = None
            else:
                result = PrepareStepResult.reject(prep_err[i])
                state = ReportAggregationState.FAILED
                blob = b""
                err = prep_err[i]
            resps.append(PrepareResp(md.report_id, result))
            report_aggs.append(
                ReportAggregationModel(
                    task.task_id, job_id, md.report_id, md.time, i, state, blob, err
                )
            )

        # accumulate accepted out shares per batch bucket (reference
        # :1811-1826); multi-round jobs accumulate at continue-finish
        accumulator = Accumulator(task, self.cfg.batch_aggregation_shard_count)
        fixed_bid = fixed_size_batch_id(req.partial_batch_selector)
        if not multi_round:
            flat_idx = None
            if idx_rows is not None:
                block_idx = np.full((n, self.wire.circ.max_blocks), -1, dtype=np.int32)
                for i, row in enumerate(idx_rows):
                    if row is not None:
                        block_idx[i] = row
                flat_idx = flat_scatter_indices(block_idx, self.wire.circ)
            with span("helper.accumulate", batch=n):
                accumulate_batched(
                    task,
                    self.engine,
                    accumulator,
                    out1,
                    accept,
                    [pi.report_share.metadata for pi in inits],
                    batch_identifier=fixed_bid,
                    flat_idx=flat_idx,
                )

        times = [pi.report_share.metadata.time.seconds for pi in inits]
        from ..trace import current_traceparent

        job = AggregationJobModel(
            task.task_id,
            job_id,
            req.aggregation_parameter,
            req.partial_batch_selector.to_bytes(),
            Interval(Time(min(times)), Duration(max(times) - min(times) + 1)) if times else Interval(Time(0), Duration(1)),
            AggregationJobState.IN_PROGRESS if multi_round else AggregationJobState.FINISHED,
            0,
            request_hash,
            # the leader's propagated traceparent: the helper's row
            # records the same job trace id the leader persisted
            trace_context=current_traceparent(),
        )

        def write(tx):
            # flush first: reports landing in collected batches become
            # individual BATCH_COLLECTED rejections (reference :86-105
            # collected-batch check + flush unmergeable set)
            unmerged = accumulator.flush_to_datastore(tx)
            tx.put_aggregation_job(job)
            for ra in report_aggs:
                if ra.report_id.data in unmerged:
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                tx.put_report_aggregation(ra)
            # conservation ledger, helper side: the RA rows ARE the
            # admission record (no client_reports on the helper); rows
            # terminal in this same tx book their outcome too. A replayed
            # init never reaches here (request-hash check above), and a
            # racing duplicate dies on the plain-INSERT PK conflict
            # before these counters commit. A non-empty aggregation
            # parameter routes both to the param-fanout lane (one
            # admission + one terminal per (report, param)).
            ledger.count_admitted(
                tx,
                task.task_id,
                len(report_aggs),
                aggregation_parameter=req.aggregation_parameter,
            )
            ledger.count_ra_outcomes(
                tx,
                task.task_id,
                report_aggs,
                unmerged,
                aggregation_parameter=req.aggregation_parameter,
            )
            return unmerged

        # last pre-commit deadline check: a budget that died during the
        # engine step means nobody is waiting for this response — drop
        # the work (the leader's fresh-lease retry replays the init
        # idempotently) rather than commit + answer into the void
        deadline_mod.check("helper_write_tx")
        with span("helper.write_tx", batch=n):
            unmerged = ds.run_tx(write, "aggregate_init")
        # e2e SLO only after the commit (a retried request must not
        # leave phantom samples); multi-round accumulates at continue
        if not multi_round:
            from .accumulator import observe_report_e2e

            observe_report_e2e(
                clock,
                [
                    pi.report_share.metadata.time
                    for i, pi in enumerate(inits)
                    if accept[i]
                    and pi.report_share.metadata.report_id.data not in unmerged
                ],
            )
        if unmerged:
            resps = [
                PrepareResp(
                    r.report_id, PrepareStepResult.reject(PrepareError.BATCH_COLLECTED)
                )
                if r.report_id.data in unmerged
                else r
                for r in resps
            ]
        return AggregationJobResp(tuple(resps))

    def _handle_aggregate_init_poplar1(
        self, ds: Datastore, clock, job_id, req, request_hash
    ) -> AggregationJobResp:
        """Helper init for Poplar1 (see poplar1_ops module docstring for
        the ping-pong mapping). Per-report host loop, like the
        reference's own prepare loops."""
        task = self.task
        pop = self.poplar
        try:
            param = pop.decode_param(req.aggregation_parameter)
        except ValueError as e:
            raise errors.InvalidMessage(f"bad aggregation parameter: {e}", task.task_id)
        F = pop.field_for(param)

        inits = list(req.prepare_inits)
        n = len(inits)
        ids = [pi.report_share.metadata.report_id for pi in inits]
        if len(set(ids)) != n:
            raise errors.InvalidMessage("duplicate report id in init request", task.task_id)

        now = clock.now()
        # param-scoped replay check: a report aggregates once PER param
        replayed_ids = ds.run_tx(
            lambda tx: tx.get_aggregated_report_ids_for_param(
                task.task_id, ids, req.aggregation_parameter
            ),
            "agg_init_replay_p1",
        )

        # (no accumulator here: Poplar1 is 2-round — out shares
        # accumulate in the continue handler when the sketch finishes)
        # Pass 1: per-report checks + HPKE + decode; eligible reports
        # collect into one batched device IDPF walk (round1_batch).
        errs: list = [None] * n
        msg1_0s: list = [None] * n
        items = []
        item_idx = []
        for i, pi in enumerate(inits):
            rs = pi.report_share
            md = rs.metadata
            err = None
            if task.task_expiration and md.time > task.task_expiration:
                err = PrepareError.TASK_EXPIRED
            elif task.report_expired(md.time, now):
                err = PrepareError.REPORT_DROPPED
            elif md.report_id.data in replayed_ids:
                err = PrepareError.REPORT_REPLAYED
            else:
                keypair = self._hpke_keypair(rs.encrypted_input_share.config_id)
                if keypair is None:
                    err = PrepareError.HPKE_UNKNOWN_CONFIG_ID
                else:
                    aad = InputShareAad(task.task_id, md, rs.public_share).to_bytes()
                    try:
                        plaintext = hpke_open(
                            keypair,
                            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER),
                            rs.encrypted_input_share,
                            aad,
                        )
                    except HpkeError:
                        err = PrepareError.HPKE_DECRYPT_ERROR
                        plaintext = None
                    if err is None:
                        try:
                            payload = PlaintextInputShare.from_bytes(plaintext).payload
                            tag, _, leader_ps = decode_pingpong(pi.message)
                            if tag != PP_INITIALIZE or leader_ps is None:
                                raise ValueError("expected ping-pong initialize")
                            msg1_0s[i] = pop.decode_fixed_vec(param, leader_ps, 2)
                            items.append((rs.public_share, payload, md.report_id.data))
                            item_idx.append(i)
                        except (DecodeError, ValueError):
                            err = PrepareError.INVALID_MESSAGE
            errs[i] = err

        round1 = {}
        for i, res in zip(item_idx, pop.round1_batch(1, items, param)):
            if isinstance(res, ValueError):
                errs[i] = PrepareError.INVALID_MESSAGE
            else:
                round1[i] = res

        # Pass 2: combine + park, same per-report results as before
        resps = []
        report_aggs = []
        for i, pi in enumerate(inits):
            rs = pi.report_share
            md = rs.metadata
            err = errs[i]
            blob = b""
            state = ReportAggregationState.FAILED
            result = None
            if err is None and i in round1:
                st1, y1, msg1_1 = round1[i]
                sigma1, combined = pop.round2(st1, msg1_0s[i], msg1_1)
                # sketch verdict needs the leader's sigma0:
                # park; validity resolves at continue time
                msg = pop.encode_vec(param, combined)
                share = pop.encode_vec(param, msg1_1) + pop.encode_elem(param, sigma1)
                blob = msg + share + pop.encode_vec(param, y1)
                state = ReportAggregationState.WAITING_HELPER
                result = PrepareStepResult.cont(encode_pingpong(PP_CONTINUE, msg, share))
            elif err is None:
                err = PrepareError.INVALID_MESSAGE
            if err is not None:
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                result = PrepareStepResult.reject(err)
            resps.append(PrepareResp(md.report_id, result))
            report_aggs.append(
                ReportAggregationModel(
                    task.task_id, job_id, md.report_id, md.time, i, state, blob, err
                )
            )

        times = [pi.report_share.metadata.time.seconds for pi in inits]
        from ..trace import current_traceparent

        job = AggregationJobModel(
            task.task_id,
            job_id,
            req.aggregation_parameter,
            req.partial_batch_selector.to_bytes(),
            Interval(Time(min(times)), Duration(max(times) - min(times) + 1))
            if times
            else Interval(Time(0), Duration(1)),
            AggregationJobState.IN_PROGRESS,
            0,
            request_hash,
            trace_context=current_traceparent(),
        )

        def write(tx):
            tx.put_aggregation_job(job)
            for ra in report_aggs:
                tx.put_report_aggregation(ra)
            # conservation ledger (see handle_aggregate_init): RA rows
            # are the helper's admission record; FAILED rows are
            # terminal already, WAITING_HELPER rows stay in-flight.
            # Poplar1 always carries a parameter, so both bookings land
            # in the param-fanout lane.
            ledger.count_admitted(
                tx,
                task.task_id,
                len(report_aggs),
                aggregation_parameter=req.aggregation_parameter,
            )
            ledger.count_ra_outcomes(
                tx,
                task.task_id,
                report_aggs,
                aggregation_parameter=req.aggregation_parameter,
            )

        ds.run_tx(write, "aggregate_init_p1")
        return AggregationJobResp(tuple(resps))

    def _replay_aggregate_init_response(self, ds: Datastore, job_id, job) -> AggregationJobResp:
        """Reconstruct the response from stored rows (reference
        check_aggregation_job_idempotence, aggregator.rs:1526).

        Only reachable while the job's last_request_hash is still the
        init request's hash — i.e. before any continue was processed
        (handle_aggregate_continue bumps the hash, so a re-PUT init
        after a continue fails the hash check instead of landing here).
        WAITING_HELPER rows therefore re-emit the same ping-pong
        CONTINUE the original init answered; FINISHED rows still hold
        their prep message in prep_blob."""
        ras = ds.run_tx(
            lambda tx: tx.get_report_aggregations_for_job(self.task.task_id, job_id),
            "agg_init_replay_resp",
        )
        if self.poplar is not None:
            # blob = enc(A)||enc(B) || enc(A1)||enc(B1)||enc(sigma1) || y1
            param = self.poplar.decode_param(job.aggregation_parameter)
            es = self.poplar.enc_size(param)
            msg_len = 2 * es

            def round1_share(blob):
                return blob[2 * es : 5 * es]
        else:
            msg_len = 16 if self.wire.uses_jr else 0

            def round1_share(blob):
                return FAKE_ROUND1_PREP_SHARE

        resps = []
        for ra in ras:
            if ra.state == ReportAggregationState.FINISHED:
                result = PrepareStepResult.cont(encode_pingpong(PP_FINISH, ra.prep_blob, None))
            elif ra.state == ReportAggregationState.WAITING_HELPER:
                result = PrepareStepResult.cont(
                    encode_pingpong(
                        PP_CONTINUE, ra.prep_blob[:msg_len], round1_share(ra.prep_blob)
                    )
                )
            else:
                result = PrepareStepResult.reject(_err_or_default(ra.prepare_error))
            resps.append(PrepareResp(ra.report_id, result))
        return AggregationJobResp(tuple(resps))

    # ------------------------------------------------------------------
    # helper aggregate continue (reference aggregation_job_continue.rs:30-300)
    # ------------------------------------------------------------------
    def handle_aggregate_continue(
        self,
        ds: Datastore,
        clock: Clock,
        job_id: AggregationJobId,
        req,
        request_bytes: bytes,
    ) -> AggregationJobResp:
        """Step a multi-round aggregation job: ord-matched prepare
        continues against stored WaitingHelper rows, step/replay
        validation, accumulate on finish."""
        import dataclasses

        task = self.task
        deadline_mod.check("helper_continue")
        if task.vdaf.rounds == 1:
            # all production Prio3 VDAFs are 1-round; a continue request
            # is always a step mismatch for them (reference parity gate)
            raise errors.StepMismatch("no multi-round VDAFs configured", task.task_id)
        request_hash = hashlib.sha256(request_bytes).digest()
        step = req.step.step
        if step == 0:
            raise errors.InvalidMessage("aggregation job cannot continue to step 0", task.task_id)

        # Everything — validation, row reads, accumulate, writes — in ONE
        # transaction: concurrent identical continues (leader timeout +
        # re-POST on a threaded server) must serialize so exactly one
        # processes and the other sees the bumped step and replays;
        # split reads would double-accumulate. `counted` carries the
        # merged-report count out of the LAST (committing) attempt for
        # the post-commit metrics increment.
        counted: dict = {}

        def process(tx):
            job = tx.get_aggregation_job(task.task_id, job_id)
            if job is None:
                raise errors.UnrecognizedAggregationJob(
                    "no such aggregation job", task.task_id
                )
            if step == job.step:
                # idempotent replay (reference aggregation_job_continue.rs
                # replay branch): same request -> same response, scoped to
                # exactly the reports the continue addressed
                if job.last_request_hash == request_hash:
                    return self._rebuild_continue_resps(tx, job_id, req)
                raise errors.StepMismatch(
                    "continue step replay with different request", task.task_id
                )
            if job.state != AggregationJobState.IN_PROGRESS:
                raise errors.StepMismatch(
                    "aggregation job is not continuable", task.task_id
                )
            if step != job.step + 1:
                raise errors.StepMismatch(
                    f"continue to step {step}, job is at step {job.step}", task.task_id
                )

            ras = tx.get_report_aggregations_for_job(task.task_id, job_id)
            all_waiting = [
                ra for ra in ras if ra.state == ReportAggregationState.WAITING_HELPER
            ]
            # ord-matched subsequence (reference :58-84): the leader's
            # prepare steps must appear in the helper's ord order; a
            # waiting report the leader omitted (failed on its side) is
            # marked ReportDropped; unexpected/duplicate/out-of-order
            # steps reject the request
            waiting = []
            dropped = []
            it = iter(all_waiting)
            for pc in req.prepare_continues:
                for ra in it:
                    if ra.report_id == pc.report_id:
                        waiting.append(ra)
                        break
                    dropped.append(ra)
                else:
                    raise errors.InvalidMessage(
                        "leader sent unexpected, duplicate, or out-of-order prepare steps",
                        task.task_id,
                    )
            dropped.extend(it)  # trailing omissions

            pop_sigma1_at = None
            if self.poplar is not None:
                # blob = enc(A)||enc(B) || enc(A1)||enc(B1)||enc(sigma1) || y1
                param = self.poplar.decode_param(job.aggregation_parameter)
                es = self.poplar.enc_size(param)
                msg_len, skip_len = es, 5 * es  # FINISH msg = enc(sigma0)

                def pop_sigma1_at(blob):
                    return blob[4 * es : 5 * es]

                field = self.poplar.field_for(param)
            else:
                msg_len = 16 if self.wire.uses_jr else 0
                skip_len = msg_len
                field = None
            # count_metrics=False: this accumulator lives inside the
            # run_tx closure — a serialization retry re-creates it and
            # would double the per-task counter; counted after commit
            # below via the `counted` cell
            accumulator = Accumulator(
                task,
                self.cfg.batch_aggregation_shard_count,
                field=field,
                aggregation_parameter=job.aggregation_parameter,
                count_metrics=False,
            )
            pbs = PartialBatchSelector.from_bytes(job.partial_batch_identifier)
            fixed_bid = fixed_size_batch_id(pbs)
            updated = []
            resps = []
            for ra, pc in zip(waiting, req.prepare_continues):
                ok = False
                try:
                    tag, prep_msg, _share = decode_pingpong(pc.message)
                    if tag != PP_FINISH:
                        ok = False
                    elif pop_sigma1_at is not None:
                        # quadratic sketch: FINISH carries the leader's
                        # sigma0; accept iff sigma0 + sigma1 == 0
                        sigma0 = self.poplar.decode_elem(param, prep_msg or b"")
                        sigma1 = self.poplar.decode_elem(param, pop_sigma1_at(ra.prep_blob))
                        ok = field.add(sigma0, sigma1) == 0
                    else:
                        ok = (prep_msg or b"") == ra.prep_blob[:msg_len]
                except (DecodeError, ValueError):
                    ok = False
                if ok:
                    out_share = accumulator.field.decode_vec(ra.prep_blob[skip_len:])
                    bid = fixed_bid or Interval(
                        ra.client_time.to_batch_interval_start(task.time_precision),
                        task.time_precision,
                    ).to_bytes()
                    accumulator.update_single(bid, out_share, ra.report_id, ra.client_time)
                    updated.append(
                        dataclasses.replace(
                            ra, state=ReportAggregationState.FINISHED, prep_blob=b""
                        )
                    )
                    resps.append(PrepareResp(ra.report_id, PrepareStepResult.finished()))
                else:
                    metrics.aggregate_step_failure_counter.add(type="vdaf_prep_error")
                    updated.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                    resps.append(
                        PrepareResp(
                            ra.report_id,
                            PrepareStepResult.reject(PrepareError.VDAF_PREP_ERROR),
                        )
                    )

            unmerged = accumulator.flush_to_datastore(tx)
            counted["n"] = accumulator.total_report_count() - len(unmerged)
            # client times of the reports that actually merged, carried
            # out of the committing attempt for the post-commit e2e
            # observation (same retry discipline as the count)
            counted["times"] = [
                ra.client_time
                for ra in updated
                if ra.state == ReportAggregationState.FINISHED
                and ra.report_id.data not in unmerged
            ]
            tx.update_aggregation_job(
                dataclasses.replace(
                    job,
                    state=AggregationJobState.FINISHED,
                    step=step,
                    last_request_hash=request_hash,
                )
            )
            dropped_terminal = [
                ra.failed(PrepareError.REPORT_DROPPED) for ra in dropped
            ]
            for ra in dropped_terminal:
                # waiting rows the leader omitted (failed on its side):
                # reference marks them ReportDropped (:72-81)
                tx.update_report_aggregation(ra)
            for ra in updated:
                tx.update_report_aggregation(
                    ra.failed(PrepareError.BATCH_COLLECTED)
                    if ra.report_id.data in unmerged
                    else ra
                )
            # conservation ledger: every addressed/omitted row reaches a
            # terminal in this tx (replays return above, before this);
            # the job's parameter routes param-fanout rows to their lane
            ledger.count_ra_outcomes(
                tx,
                task.task_id,
                updated + dropped_terminal,
                unmerged,
                aggregation_parameter=job.aggregation_parameter,
            )
            if unmerged:
                resps = [
                    PrepareResp(
                        r.report_id,
                        PrepareStepResult.reject(PrepareError.BATCH_COLLECTED),
                    )
                    if r.report_id.data in unmerged
                    else r
                    for r in resps
                ]
            return AggregationJobResp(tuple(resps))

        resp = ds.run_tx(process, "aggregate_continue")
        from .accumulator import count_reports_aggregated, observe_report_e2e

        count_reports_aggregated(task.task_id, counted.get("n", 0))
        observe_report_e2e(clock, counted.get("times", ()))
        return resp

    def _rebuild_continue_resps(self, tx, job_id, req) -> AggregationJobResp:
        """Replay response scoped to exactly the reports the continue
        request addressed, in request order (init-time failures are NOT
        part of a continue response — reference reconstructs only the
        addressed steps)."""
        ras = {
            ra.report_id: ra
            for ra in tx.get_report_aggregations_for_job(self.task.task_id, job_id)
        }
        resps = []
        for pc in req.prepare_continues:
            ra = ras.get(pc.report_id)
            if ra is None:
                continue
            if ra.state == ReportAggregationState.FINISHED:
                resps.append(PrepareResp(ra.report_id, PrepareStepResult.finished()))
            else:
                resps.append(
                    PrepareResp(
                        ra.report_id,
                        PrepareStepResult.reject(
                            _err_or_default(ra.prepare_error)
                        ),
                    )
                )
        return AggregationJobResp(tuple(resps))

    # ------------------------------------------------------------------
    # collection jobs (leader; reference aggregator.rs:2185-2746)
    # ------------------------------------------------------------------
    def handle_create_collection_job(
        self, ds: Datastore, collection_job_id: CollectionJobId, req: CollectionReq
    ) -> None:
        task = self.task
        if req.query.query_type != task.query_type.code:
            raise errors.InvalidMessage("query type mismatch", task.task_id)
        if self.poplar is not None:
            # reject malformed parameters at creation, not as silent
            # driver abandonment ten lease attempts later
            try:
                self.poplar.decode_param(req.aggregation_parameter)
            except ValueError as e:
                raise errors.InvalidMessage(
                    f"bad aggregation parameter: {e}", task.task_id
                )
        elif req.aggregation_parameter != b"" and not task.vdaf.kind.startswith("fake"):
            # fakes mirror the reference's dummy_vdaf, which accepts
            # arbitrary parameters; real Prio3 parameters are empty
            raise errors.InvalidMessage(
                "nonempty aggregation parameter for a parameterless VDAF",
                task.task_id,
            )
        from ..messages import FixedSizeQuery

        current_batch = False
        if req.query.query_type == TimeInterval.CODE:
            interval = req.query.batch_interval
            if not interval.aligned_to(task.time_precision):
                raise errors.BatchInvalid("unaligned batch interval", task.task_id)
            if interval.duration.seconds < task.time_precision.seconds:
                raise errors.BatchInvalid("batch interval too small", task.task_id)
            batch_identifier = interval.to_bytes()
        elif req.query.fixed_size_query.kind == FixedSizeQuery.BY_BATCH_ID:
            batch_identifier = req.query.fixed_size_query.batch_id.data
        else:
            current_batch = True  # batch resolved inside the tx
            batch_identifier = None

        def create(tx):
            # current-batch queries are byte-identical across requests, so
            # their idempotency key is the collection job id, not the query
            # (reference fixed-size current-batch acquisition,
            # aggregator.rs:2185-2485 / query_type.rs FixedSize)
            if current_batch:
                existing = tx.get_collection_job(task.task_id, collection_job_id)
                if existing is not None:
                    if existing.query != req.query.to_bytes():
                        raise errors.InvalidMessage(
                            "collection job id reuse", task.task_id
                        )
                    return  # idempotent retry of the same request
                chosen = None
                for ob in tx.get_outstanding_batches(task.task_id, include_filled=True):
                    # gate on ACTUALLY AGGREGATED reports, not assigned ones:
                    # assigned reports can fail prepare, and consuming a
                    # batch that can never reach min_batch_size strands it
                    aggregated = tx.sum_batch_aggregation_report_count(
                        task.task_id, ob.batch_id.data, req.aggregation_parameter
                    )
                    if aggregated >= task.min_batch_size:
                        chosen = ob
                        break
                if chosen is None:
                    raise errors.BatchInvalid(
                        "no batch ready for collection", task.task_id
                    )
                tx.delete_outstanding_batch(task.task_id, chosen.batch_id)
                bid = chosen.batch_id.data
            else:
                existing = tx.find_collection_job_by_query(
                    task.task_id, req.query.to_bytes(), req.aggregation_parameter
                )
                if existing is not None:
                    if existing.collection_job_id != collection_job_id:
                        raise errors.BatchOverlap("query already collected under another job", task.task_id)
                    return
                if tx.get_collection_job(task.task_id, collection_job_id) is not None:
                    raise errors.InvalidMessage("collection job id reuse", task.task_id)
                bid = batch_identifier

            # Leader-side collect validation (reference
            # query_type.rs:204 CollectableQueryType collectability +
            # aggregator.rs:2185-2485). Without it a misbehaving
            # collector gets unbounded leader work and the privacy
            # budget is enforced only by the helper. Deleted jobs still
            # count: their batches were (or may have been) released, so
            # the budget is spent.
            if req.query.query_type == TimeInterval.CODE:
                # overlap with DISTINCT prior batches only — re-querying
                # the same interval (different agg param) is governed by
                # the query-count check below, not overlap
                for other_bid, _query, _state in tx.get_collection_job_batches_for_task(
                    task.task_id
                ):
                    if other_bid == bid:
                        continue
                    other = Interval.from_bytes(other_bid)
                    if (
                        interval.start.seconds < other.end.seconds
                        and other.start.seconds < interval.end.seconds
                    ):
                        raise errors.BatchOverlap(
                            "batch interval overlaps a previously collected interval",
                            task.task_id,
                        )
            queried = tx.count_collection_jobs_for_batch(task.task_id, bid)
            if queried >= task.max_batch_query_count:
                raise errors.BatchQueryCountExceeded(
                    "batch has reached max_batch_query_count", task.task_id
                )
            from ..trace import current_traceparent

            tx.put_collection_job(
                CollectionJobModel(
                    task.task_id,
                    collection_job_id,
                    req.query.to_bytes(),
                    req.aggregation_parameter,
                    bid,
                    CollectionJobState.START,
                    # the dap.collection_create handler span's context:
                    # the collection job driver adopts it on every step
                    trace_context=current_traceparent(),
                )
            )

        ds.run_tx(create, "create_collection_job")

    def handle_get_collection_job(self, ds: Datastore, collection_job_id: CollectionJobId):
        """-> (ready: bool, Collection | None)."""
        task = self.task
        job = ds.run_tx(
            lambda tx: tx.get_collection_job(task.task_id, collection_job_id),
            "get_collection_job",
        )
        if job is None or job.state == CollectionJobState.DELETED:
            raise errors.UnrecognizedCollectionJob("no such collection job", task.task_id)
        if job.state in (CollectionJobState.START, CollectionJobState.COLLECTABLE):
            return False, None
        if job.state == CollectionJobState.ABANDONED:
            raise errors.AggregatorError("collection job abandoned", task.task_id)
        # FINISHED: leader share is sealed to the collector here
        from ..messages import PartialBatchSelector, Query

        query = Query.from_bytes(job.query)
        if query.query_type == TimeInterval.CODE:
            pbs = PartialBatchSelector.time_interval()
            batch_selector = BatchSelector.time_interval(Interval.from_bytes(job.batch_identifier))
        else:
            from ..messages import BatchId

            pbs = PartialBatchSelector.fixed_size(BatchId(job.batch_identifier))
            batch_selector = BatchSelector.fixed_size(BatchId(job.batch_identifier))
        aad = AggregateShareAad(task.task_id, job.aggregation_parameter, batch_selector).to_bytes()
        leader_enc = hpke_seal(
            task.collector_hpke_config,
            HpkeApplicationInfo(Label.AGGREGATE_SHARE, Role.LEADER, Role.COLLECTOR),
            job.leader_aggregate_share,
            aad,
        )
        helper_enc = HpkeCiphertext.from_bytes(job.helper_encrypted_aggregate_share)
        return True, Collection(
            pbs, job.report_count, job.client_timestamp_interval, leader_enc, helper_enc
        )

    def handle_delete_collection_job(self, ds: Datastore, collection_job_id: CollectionJobId) -> None:
        import dataclasses

        task = self.task

        def delete(tx):
            job = tx.get_collection_job(task.task_id, collection_job_id)
            if job is None:
                raise errors.UnrecognizedCollectionJob("no such collection job", task.task_id)
            tx.update_collection_job(
                dataclasses.replace(job, state=CollectionJobState.DELETED)
            )

        ds.run_tx(delete, "delete_collection_job")

    # ------------------------------------------------------------------
    # aggregate share (helper; reference aggregator.rs:2747-2980)
    # ------------------------------------------------------------------
    def handle_aggregate_share(self, ds: Datastore, req: AggregateShareReq) -> AggregateShare:
        task = self.task
        deadline_mod.check("helper_aggregate_share")
        failpoints.hit("helper.aggregate_share")
        if req.batch_selector.query_type != task.query_type.code:
            raise errors.InvalidMessage("query type mismatch", task.task_id)
        if req.batch_selector.query_type == TimeInterval.CODE:
            interval = req.batch_selector.batch_interval
            if not interval.aligned_to(task.time_precision):
                raise errors.BatchInvalid("unaligned batch interval", task.task_id)
            batch_identifier = interval.to_bytes()
        else:
            batch_identifier = req.batch_selector.batch_id.data

        if self.poplar is not None:
            try:
                p1_param = self.poplar.decode_param(req.aggregation_parameter)
            except ValueError as e:
                raise errors.InvalidMessage(f"bad aggregation parameter: {e}", task.task_id)
            share_field = self.poplar.field_for(p1_param)
        else:
            share_field = self.circ.FIELD

        def compute(tx):
            existing = tx.get_aggregate_share_job(
                task.task_id, batch_identifier, req.aggregation_parameter
            )
            if existing is not None:
                return existing, False
            # enforce query count (reference max_batch_query_count)
            count = tx.count_aggregate_share_jobs_for_batch(task.task_id, batch_identifier)
            if count >= task.max_batch_query_count:
                raise errors.BatchQueryCountExceeded("batch queried too many times", task.task_id)
            # gather the helper's own shard rows
            if req.batch_selector.query_type == TimeInterval.CODE:
                rows = tx.get_batch_aggregations_intersecting_interval(
                    task.task_id,
                    Interval.from_bytes(batch_identifier),
                    aggregation_parameter=req.aggregation_parameter,
                )
            else:
                rows = tx.get_batch_aggregations_for_batch(
                    task.task_id, batch_identifier, req.aggregation_parameter
                )
            share = None
            total = 0
            checksum = ReportIdChecksum()
            for row in rows:
                share = add_encoded_aggregate_shares(share_field, share, row.aggregate_share)
                total += row.report_count
                checksum = checksum.combined_with(row.checksum)
                tx.mark_batch_aggregations_collected(
                    task.task_id, row.batch_identifier, row.aggregation_parameter
                )
            # conservation ledger: only rows still uncollected at gather
            # time book `collected` — a re-query of the batch
            # (max_batch_query_count > 1) adds nothing, and a failed tx
            # (mismatch/size errors below) books nothing
            ledger.count_collected(tx, task.task_id, rows)
            if share is None:
                raise errors.BatchInvalid("no aggregated reports in batch", task.task_id)
            # leader/helper consistency (reference checksum/count match)
            if total != req.report_count or checksum != req.checksum:
                raise errors.BatchMismatch(
                    f"count/checksum mismatch: ours {total}, leader {req.report_count}",
                    task.task_id,
                )
            if total < task.min_batch_size:
                raise errors.InvalidBatchSize(f"batch too small: {total}", task.task_id)
            # DP: noise the helper's share once, before it is persisted or
            # released (count/checksum stay exact; only the share is noised)
            from ..dp import add_noise_to_agg_share

            share = add_noise_to_agg_share(task.dp_strategy, share_field, share)
            job = AggregateShareJob(
                task.task_id,
                batch_identifier,
                req.aggregation_parameter,
                share,
                total,
                checksum,
            )
            tx.put_aggregate_share_job(job)
            return job, True

        job, _ = ds.run_tx(compute, "aggregate_share")
        aad = AggregateShareAad(
            task.task_id, req.aggregation_parameter, req.batch_selector
        ).to_bytes()
        encrypted = hpke_seal(
            task.collector_hpke_config,
            HpkeApplicationInfo(Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR),
            job.helper_aggregate_share,
            aad,
        )
        return AggregateShare(encrypted)


class Aggregator:
    """Top-level request router over tasks (reference aggregator.rs:156)."""

    def __init__(self, ds: Datastore, clock: Clock | None = None, cfg: Config | None = None):
        from .cache import GlobalHpkeKeypairCache, PeerAggregatorCache
        from .report_writer import ReportWriteBatcher

        import threading

        self.ds = ds
        self.clock = clock or RealClock()
        self.cfg = cfg or Config()
        self._task_aggs: dict[bytes, TaskAggregator] = {}
        # guards the cache INSERT (first-insert-wins): a concurrent
        # upload burst on a fresh task used to hand each handler thread
        # its OWN TaskAggregator — and since the ingest decrypt stage
        # groups a window's lanes by task identity, a first-burst
        # window degenerated into singleton "batches"
        self._task_aggs_lock = threading.Lock()
        self.global_hpke_keypairs = GlobalHpkeKeypairCache(ds)
        self.peer_aggregators = PeerAggregatorCache(ds) if self.cfg.taskprov_enabled else None
        # datastore-outage survival: with a journal path configured the
        # report writer spills to the durable on-disk journal when the
        # datastore is unreachable, and a background replayer drains it
        # back on recovery (janus_tpu.ingest.journal)
        self.upload_journal = None
        self.journal_replayer = None
        if self.cfg.upload_journal_path:
            from ..ingest.journal import JournalReplayer, UploadJournal

            self.upload_journal = UploadJournal(
                self.cfg.upload_journal_path,
                ds.crypter,
                max_segment_bytes=self.cfg.upload_journal_max_segment_bytes,
                max_total_bytes=self.cfg.upload_journal_max_total_bytes,
                max_segments=self.cfg.upload_journal_max_segments,
                full_retry_after_s=self.cfg.upload_journal_full_retry_after_s,
            )
        self.report_writer = ReportWriteBatcher(
            ds,
            self.cfg.max_upload_batch_size,
            self.cfg.max_upload_batch_write_delay_ms,
            journal=self.upload_journal,
            spill_latency_s=self.cfg.upload_journal_spill_latency_s,
        )
        if self.upload_journal is not None:
            from ..binary_utils import register_readiness_check
            from ..statusz import register_status_provider

            self.journal_replayer = JournalReplayer(
                self.upload_journal,
                self.report_writer,
                supervisor_fn=lambda: getattr(self.ds, "supervisor", None),
                interval_s=self.cfg.upload_journal_replay_interval_s,
            ).start()
            register_status_provider("upload_journal", self.upload_journal.status)
            # /readyz fails while the journal is full: this replica can
            # no longer honor 201s through an outage
            register_readiness_check("upload_journal", self.upload_journal.readiness)

    def close(self) -> None:
        """Shutdown: stop the journal replayer and flush/stop the report
        writer (any uploads still buffered in the group-commit writer
        land before exit; journaled ones survive on disk and replay on
        the next boot)."""
        if self.journal_replayer is not None:
            self.journal_replayer.stop()
        self.report_writer.close()
        if self.upload_journal is not None:
            from ..binary_utils import unregister_readiness_check
            from ..statusz import unregister_status_provider

            unregister_readiness_check("upload_journal")
            unregister_status_provider("upload_journal")
            self.upload_journal.close()

    def task_aggregator_for(
        self, task_id: TaskId, taskprov_task_config=None, headers=None, peer_role: Role = Role.LEADER
    ) -> TaskAggregator:
        """peer_role: role the requesting peer plays when provisioning
        via taskprov — the HTTP handler knows which endpoint was hit
        (helper endpoints are called by the leader, so Role.LEADER)."""
        ta = self._task_aggs.get(task_id.data)
        if ta is None:
            task = self.ds.run_tx(lambda tx: tx.get_task(task_id), "get_task")
            if task is None:
                if self.cfg.taskprov_enabled and taskprov_task_config is not None:
                    # opt in, then retry (reference aggregator.rs:368-381)
                    self.taskprov_opt_in(
                        peer_role, task_id, taskprov_task_config, headers or {}
                    )
                    task = self.ds.run_tx(lambda tx: tx.get_task(task_id), "get_task")
                if task is None:
                    raise errors.UnrecognizedTask("unknown task", task_id)
            # first-insert-wins (the engine_cache idiom): construction
            # touches circuit/engine lookup and must not serialize
            # unrelated tasks' cold starts behind one global lock —
            # racing builders each construct, the first insert wins,
            # and every caller returns the SAME object so the ingest
            # decrypt stage's (task, config) batch grouping holds
            candidate = TaskAggregator(task, self.cfg, self.global_hpke_keypairs)
            with self._task_aggs_lock:
                ta = self._task_aggs.setdefault(task_id.data, candidate)
        return ta

    # ------------------------------------------------------------------
    # taskprov (reference aggregator.rs:639-776)
    # ------------------------------------------------------------------
    def taskprov_authorize_request(self, peer_role: Role, task_id: TaskId, task_config, headers):
        """Validate + authenticate a taskprov request against the
        pre-shared peer; returns the PeerAggregator
        (reference taskprov_authorize_request, aggregator.rs:724)."""
        urls = task_config.aggregator_endpoints
        if len(urls) != 2:
            raise errors.InvalidMessage(
                "taskprov configuration is missing one or both aggregators", task_id
            )
        peer_url = urls[0] if peer_role == Role.LEADER else urls[1]
        peer = self.peer_aggregators.get(peer_url, peer_role) if self.peer_aggregators else None
        if peer is None:
            raise errors.InvalidTask(f"no such peer aggregator {peer_url}", task_id)
        if not peer.check_aggregator_auth(headers or {}):
            raise errors.UnauthorizedRequest("bad taskprov aggregator auth", task_id)
        if self.clock.now() > task_config.task_expiration:
            raise errors.InvalidTask("task expired", task_id)
        return peer

    def taskprov_opt_in(self, peer_role: Role, task_id: TaskId, task_config, headers) -> None:
        """Provision a task from an in-band TaskConfig
        (reference taskprov_opt_in, aggregator.rs:641-719)."""
        from ..messages.taskprov import TaskprovQueryType
        from ..task import QueryTypeConfig

        peer = self.taskprov_authorize_request(peer_role, task_id, task_config, headers)
        try:
            vdaf_instance = task_config.vdaf_config.vdaf_type.to_vdaf_instance()
            # gate BEFORE persisting: a task whose circuit can never be
            # built (e.g. Poplar1, which needs nontrivial aggregation
            # parameters) must be a clean InvalidTask rejection, not a
            # poisoned stored task that 500s forever
            circuit_for(vdaf_instance)
        except ValueError as e:
            raise errors.InvalidTask(str(e), task_id)
        our_role = Role.HELPER if peer_role == Role.LEADER else Role.LEADER
        verify_key = peer.derive_vdaf_verify_key(task_id)

        qc = task_config.query_config
        if qc.query_type == TaskprovQueryType.TIME_INTERVAL:
            query_type = QueryTypeConfig.time_interval()
        elif qc.query_type == TaskprovQueryType.FIXED_SIZE:
            query_type = QueryTypeConfig.fixed_size(max_batch_size=qc.max_batch_size)
        else:
            raise errors.InvalidTask(f"unsupported query type {qc.query_type}", task_id)

        task = Task(
            task_id=task_id,
            leader_aggregator_endpoint=task_config.leader_url(),
            helper_aggregator_endpoint=task_config.helper_url(),
            query_type=query_type,
            vdaf=vdaf_instance,
            role=our_role,
            vdaf_verify_key=verify_key,
            max_batch_query_count=qc.max_batch_query_count,
            task_expiration=task_config.task_expiration,
            report_expiry_age=peer.report_expiry_age,
            min_batch_size=qc.min_batch_size,
            time_precision=qc.time_precision,
            tolerable_clock_skew=peer.tolerable_clock_skew,
            collector_hpke_config=peer.collector_hpke_config,
            aggregator_auth_token=None,  # peer tokens authenticate taskprov
            collector_auth_token=None,
            hpke_keys=(),  # taskprov tasks use global HPKE keys
        )

        def put(tx):
            # concurrent opt-in by another replica is benign (reference
            # aggregator.rs:699-707): same config -> same task
            if tx.get_task(task_id) is None:
                tx.put_task(task)

        self.ds.run_tx(put, "taskprov_put_task")

    # role/auth checks used by the HTTP layer
    def check_aggregator_auth(self, task: Task, headers) -> None:
        tok = task.aggregator_auth_token
        if tok is None or not tok.matches_headers(headers):
            raise errors.UnauthorizedRequest("bad aggregator auth", task.task_id)

    def check_collector_auth(self, task: Task, headers) -> None:
        tok = task.collector_auth_token
        if tok is None or not tok.matches_headers(headers):
            raise errors.UnauthorizedRequest("bad collector auth", task.task_id)
