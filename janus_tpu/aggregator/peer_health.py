"""Peer-outage parking: stop claiming jobs while the other aggregator
is down, and resume them with a cheap half-open probe.

The outbound circuit breaker (core/circuit_breaker.py) already makes a
dead helper cheap *per step*: a claimed job fails fast with
CircuitOpenError and steps back. But step-backs still churn — every
driver worker keeps acquiring leases, opening transactions, releasing
with reason `circuit_open`, and re-sleeping, for as long as the outage
lasts. The datastore outage discipline (job_driver.py
`acquire_tolerating_outage`) showed the better shape: when the
dependency is KNOWN to be down, park the acquirer itself — no claim
transaction, no lease, no churn — and let a cheap probe resume it.

This module extends that discipline to the peer:

* `PeerHealthTracker.observe_endpoint(url)` — both job drivers register
  the helper endpoint of every task they step, so the tracker knows the
  peer universe and where to aim probes.
* `park_gate()` — plugs into `make_claim_acquirer(..., peer_gate=...)`.
  Claims park while EVERY known peer's breaker is not closed: in the
  common single-helper deployment one dead peer parks the driver
  outright; with several helpers a partial outage falls back to the
  per-step breaker step-backs (a claim might target a healthy peer, so
  parking would strand live work — documented limitation).
* a background prober (`start()`/`stop()`) ticks every
  `probe_interval_s`: it accrues `janus_peer_outage_seconds_total`,
  publishes `janus_peer_parked`, and issues the half-open probe itself —
  one cheap GET through the breaker's single probe slot
  (`check()` admits it, any HTTP status counts as alive) so recovery
  does not wait for a parked driver to stumble into the peer.

State exports as `janus_peer_parked{peer}` /
`janus_peer_outage_seconds_total{peer}` / `janus_peer_probes_total`
plus a `peer_health` /statusz section; slo.py's `peer_reachable`
builtin burns while any peer is parked. docs/ARCHITECTURE.md
"Surviving the other aggregator" has the full contract.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from ..core.circuit_breaker import CLOSED, OutboundCircuitBreakers, peer_label

log = logging.getLogger(__name__)

PROBE_ALIVE = "alive"
PROBE_DEAD = "dead"
PROBE_REJECTED = "rejected"


@dataclass(frozen=True)
class PeerHealthConfig:
    """YAML `peer_health:` section of the job driver binaries
    (config.py JobDriverBinaryConfig)."""

    enabled: bool = True
    # park claim acquisition while all known peers are non-closed; off =
    # probe + export state only, keep the per-step breaker step-backs
    park: bool = True
    # background prober cadence (also the outage-seconds accrual grain)
    probe_interval_s: float = 5.0
    # budget for one probe GET; probes are cheap by contract
    probe_timeout_s: float = 5.0

    @classmethod
    def from_dict(cls, d: dict | None) -> "PeerHealthConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            park=bool(d.get("park", True)),
            probe_interval_s=float(d.get("probe_interval_secs", 5.0)),
            probe_timeout_s=float(d.get("probe_timeout_secs", 5.0)),
        )


class PeerHealthTracker:
    """Shared by both job drivers in one process (like the breaker
    registry it wraps): a helper that is down for aggregation steps is
    down for aggregate-share fetches too, and both acquirers park
    together."""

    def __init__(
        self,
        breakers: OutboundCircuitBreakers,
        cfg: PeerHealthConfig | None = None,
        http=None,
    ):
        self.breakers = breakers
        self.cfg = cfg or PeerHealthConfig()
        # fetch_any_status-compatible override for tests; None = the
        # real core.http_client.fetch_any_status
        self._http = http
        self._lock = threading.Lock()
        # peer label -> probe URL (the task's helper endpoint; any HTTP
        # answer from it — 404 included — proves the peer routes and
        # talks protocol)
        self._endpoints: dict[str, str] = {}
        # peer label -> monotonic timestamp of the last outage accrual
        self._last_accrual: dict[str, float] = {}
        self._parked_since: float | None = None
        self._outage_started: dict[str, float] = {}
        self._probe_counts: dict[str, dict[str, int]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    # driver-facing surface
    # ------------------------------------------------------------------
    def observe_endpoint(self, url: str) -> str:
        """Register a helper endpoint (called from the drivers' send
        paths before the breaker check, so even a peer that never
        answered once is probeable). Returns its peer label."""
        peer = peer_label(url)
        with self._lock:
            self._endpoints.setdefault(peer, url)
        return peer

    def parked_peers(self) -> list[str]:
        """Peers whose breaker is currently not closed."""
        states = self.breakers.peer_states()
        return sorted(p for p, s in states.items() if s != CLOSED)

    def should_park(self) -> bool:
        """True while claim acquisition should park: parking enabled,
        at least one peer known, and EVERY known peer non-closed."""
        if not (self.cfg.enabled and self.cfg.park):
            return False
        states = self.breakers.peer_states()
        if not states:
            return False
        return all(s != CLOSED for s in states.values())

    def park_gate(self):
        """The callable for make_claim_acquirer(..., peer_gate=...)."""
        return self.should_park

    # ------------------------------------------------------------------
    # the prober
    # ------------------------------------------------------------------
    def tick(self, now: float | None = None) -> None:
        """One prober beat: accrue outage seconds, publish the parked
        gauge, probe whatever is probeable. Exposed for tests and for
        the chaos harness; the background thread just loops it."""
        from .. import metrics

        if now is None:
            now = time.monotonic()
        states = self.breakers.peer_states()
        parked = self.should_park()
        with self._lock:
            self._parked_since = (
                (self._parked_since or now) if parked else None
            )
            for peer, state in states.items():
                down = state != CLOSED
                metrics.peer_parked.set(1.0 if down else 0.0, peer=peer)
                last = self._last_accrual.get(peer)
                if down:
                    self._outage_started.setdefault(peer, now)
                    if last is not None:
                        metrics.peer_outage_seconds_total.add(
                            max(0.0, now - last), peer=peer
                        )
                    self._last_accrual[peer] = now
                else:
                    self._outage_started.pop(peer, None)
                    self._last_accrual.pop(peer, None)
        for peer, state in states.items():
            if state != CLOSED and self.breakers.retry_in_s(peer) == 0.0:
                self.probe(peer)

    def probe(self, peer: str) -> str:
        """One cheap half-open probe through the breaker's single probe
        slot. Returns the outcome ("alive"/"dead"/"rejected")."""
        from ..core.circuit_breaker import CircuitOpenError
        from .. import metrics

        with self._lock:
            url = self._endpoints.get(peer)
        if url is None:
            return PROBE_REJECTED
        try:
            self.breakers.check(peer)
        except CircuitOpenError:
            # cooldown not elapsed, or another probe (possibly a real
            # driver step) holds the half-open slot — don't stampede
            outcome = PROBE_REJECTED
        else:
            try:
                fetch = self._http
                if fetch is None:
                    from ..core.http_client import fetch_any_status as fetch
                status, _ = fetch(url, timeout=self.cfg.probe_timeout_s)
            except Exception as e:
                log.warning("peer probe %s (%s) failed: %s", peer, url, e)
                self.breakers.record_failure(peer)
                outcome = PROBE_DEAD
            else:
                # ANY status is a live peer: it routed, accepted the
                # connection, and spoke HTTP — 404/405 on the task
                # endpoint is normal for a GET probe
                log.info("peer probe %s answered %d: resuming", peer, status)
                self.breakers.record_success(peer)
                outcome = PROBE_ALIVE
        metrics.peer_probes_total.add(peer=peer, outcome=outcome)
        with self._lock:
            counts = self._probe_counts.setdefault(
                peer, {PROBE_ALIVE: 0, PROBE_DEAD: 0, PROBE_REJECTED: 0}
            )
            counts[outcome] += 1
        return outcome

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("peer health tick failed")

    def start(self) -> None:
        if not self.cfg.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="peer-health-prober", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.cfg.probe_interval_s + 5.0)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def status(self) -> dict:
        """/statusz `peer_health` section body. Must never raise."""
        now = time.monotonic()
        states = self.breakers.peer_states()
        with self._lock:
            endpoints = dict(self._endpoints)
            outage_started = dict(self._outage_started)
            probe_counts = {p: dict(c) for p, c in self._probe_counts.items()}
            parked_since = self._parked_since
        parked = self.should_park()
        return {
            "config": {
                "enabled": self.cfg.enabled,
                "park": self.cfg.park,
                "probe_interval_s": self.cfg.probe_interval_s,
                "probe_timeout_s": self.cfg.probe_timeout_s,
            },
            "parked": parked,
            "parked_for_s": round(now - parked_since, 3)
            if parked and parked_since is not None
            else 0.0,
            "peers": {
                peer: {
                    "state": states.get(peer, "unknown"),
                    "endpoint": endpoints.get(peer),
                    "outage_for_s": round(now - outage_started[peer], 3)
                    if peer in outage_started
                    else 0.0,
                    "probes": probe_counts.get(
                        peer,
                        {PROBE_ALIVE: 0, PROBE_DEAD: 0, PROBE_REJECTED: 0},
                    ),
                }
                for peer in sorted(set(states) | set(endpoints))
            },
        }


# Process-wide default tracker, shared by both job drivers (mirrors
# default_breakers: the first caller's config wins) and exposed on
# /statusz as `peer_health`.
_default_lock = threading.Lock()
_default: PeerHealthTracker | None = None


def default_tracker(
    breakers: OutboundCircuitBreakers,
    cfg: PeerHealthConfig | None = None,
) -> PeerHealthTracker:
    global _default
    with _default_lock:
        if _default is None:
            _default = PeerHealthTracker(breakers, cfg)
            from ..statusz import register_status_provider

            register_status_provider("peer_health", _default.status)
        elif cfg is not None and _default.cfg == PeerHealthConfig():
            _default.cfg = cfg
        return _default


def reset_default_tracker() -> None:
    """Test hook: stop the prober and drop the process-wide tracker."""
    global _default
    with _default_lock:
        if _default is not None:
            _default.stop()
        _default = None
