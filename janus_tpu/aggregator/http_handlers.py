"""DAP HTTP layer: routes, media types, auth, problem details.

Equivalent of reference aggregator/src/aggregator/http_handlers.rs:
205-268 (trillium router) on the Python stdlib threading HTTP server:

  GET  /hpke_config?task_id=...
  PUT  /tasks/:task_id/reports
  PUT  /tasks/:task_id/aggregation_jobs/:aggregation_job_id
  POST /tasks/:task_id/aggregation_jobs/:aggregation_job_id  (continue)
  PUT  /tasks/:task_id/collection_jobs/:collection_job_id
  POST /tasks/:task_id/collection_jobs/:collection_job_id    (poll)
  DELETE /tasks/:task_id/collection_jobs/:collection_job_id
  POST /tasks/:task_id/aggregate_shares

Errors map to RFC 7807 problem documents (problem_details.rs).
"""

from __future__ import annotations

import base64
import json
import logging
import math
import re
import threading
from http.server import BaseHTTPRequestHandler

from ..core.deadline import DEADLINE_EXCEEDED_STATUS, DeadlineExceeded
from ..ingest import AdmissionConfig, AdmissionController, IngestPipeline, ShedError
from ..messages import (
    AggregateShareReq,
    AggregationJobId,
    AggregationJobInitializeReq,
    CollectionJobId,
    CollectionReq,
    Role,
    TaskId,
)
from ..messages.codec import DecodeError
from ..core.time_util import Clock
from .core import Aggregator
from .errors import AggregatorError, InvalidMessage, UnrecognizedTask

# Advertises the sender's XOF framing mode on aggregation-job requests
# so a leader/helper mode mismatch fails loudly instead of rejecting
# every report (ADVICE: framing-version identifier).
XOF_MODE_HEADER = "janus-xof-mode"

log = logging.getLogger(__name__)


def _b64dec(s: str, size: int) -> bytes:
    raw = base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
    if len(raw) != size:
        raise DecodeError(f"bad id length {len(raw)}")
    return raw


_ROUTES = [
    ("GET", re.compile(r"^/hpke_config$"), "hpke_config"),
    ("PUT", re.compile(r"^/tasks/([^/]+)/reports$"), "upload"),
    ("PUT", re.compile(r"^/tasks/([^/]+)/aggregation_jobs/([^/]+)$"), "aggregate_init"),
    ("POST", re.compile(r"^/tasks/([^/]+)/aggregation_jobs/([^/]+)$"), "aggregate_continue"),
    ("PUT", re.compile(r"^/tasks/([^/]+)/collection_jobs/([^/]+)$"), "collection_create"),
    ("POST", re.compile(r"^/tasks/([^/]+)/collection_jobs/([^/]+)$"), "collection_poll"),
    ("DELETE", re.compile(r"^/tasks/([^/]+)/collection_jobs/([^/]+)$"), "collection_delete"),
    ("POST", re.compile(r"^/tasks/([^/]+)/aggregate_shares$"), "aggregate_share"),
    # cross-aggregator ledger reconciliation (janus_tpu/ledger.py): the
    # leader's collection driver reads the helper's per-batch counts
    # over the same aggregator-auth channel as the DAP steps
    ("GET", re.compile(r"^/tasks/([^/]+)/ledger$"), "ledger"),
]

# Admission route classes (docs/INGEST.md shed policy): client uploads
# shed first; the aggregator-to-aggregator steps — which finish work
# the system already paid to admit — shed only near saturation.
# hpke_config (cheap, cacheable) and the collector-facing
# collection_jobs routes (which have their own 202 Retry-After flow)
# are never shed.
_ROUTE_CLASS = {
    "upload": "upload",
    "aggregate_init": "aggregate",
    "aggregate_continue": "aggregate",
    "aggregate_share": "aggregate",
}

# Request body media types per route (reference http_handlers.rs:512-551
# extracts and enforces the DAP media type on every body-carrying route).
import functools


@functools.lru_cache(maxsize=1)
def _request_media_types():
    from ..messages import (
        AggregateShareReq as ASR,
        AggregationJobContinueReq as AJCR,
        AggregationJobInitializeReq as AJIR,
        CollectionReq as CR,
        Report as R,
    )

    return {
        "upload": R.MEDIA_TYPE,
        "aggregate_init": AJIR.MEDIA_TYPE,
        "aggregate_continue": AJCR.MEDIA_TYPE,
        "collection_create": CR.MEDIA_TYPE,
        "aggregate_share": ASR.MEDIA_TYPE,
    }

# Browser-reachable routes get CORS preflights (reference
# http_handlers.rs:236-259 adds preflight handlers for hpke_config,
# upload, and the collector-facing collection_jobs routes).
_CORS_ROUTES = [
    (re.compile(r"^/hpke_config$"), "GET"),
    (re.compile(r"^/tasks/([^/]+)/reports$"), "PUT"),
    (re.compile(r"^/tasks/([^/]+)/collection_jobs/([^/]+)$"), "PUT, POST, DELETE"),
]


def _cors_allow(path: str) -> str | None:
    """Allowed methods for a CORS-enabled path, else None (single source
    for both the preflight status and the response headers)."""
    for rx, allow in _CORS_ROUTES:
        if rx.match(path):
            return allow
    return None


class DapHttpApp:
    """Routing + handler glue around an Aggregator.

    Uploads flow through an admission-controlled ingest pipeline
    (janus_tpu.ingest): shed requests answer `429 + Retry-After`
    before any crypto work; admitted ones decode/decrypt/commit on the
    pipeline's bounded worker stages while the handler thread parks on
    the ticket. Built lazily from the aggregator's Config on the first
    admitted route, so test doubles that never reach a real handler
    need no config."""

    def __init__(self, aggregator: Aggregator, ingest: IngestPipeline | None = None):
        self.agg = aggregator
        self._ingest = ingest
        self._admission: AdmissionController | None = None
        self._ingest_lock = threading.Lock()

    def _ensure_ingest(self) -> tuple[IngestPipeline, AdmissionController]:
        with self._ingest_lock:
            if self._ingest is None:
                cfg = self.agg.cfg
                self._ingest = IngestPipeline(
                    self.agg.report_writer,
                    decrypt_workers=cfg.ingest_decrypt_workers,
                    decode_workers=cfg.ingest_decode_workers,
                    queue_depth=cfg.ingest_queue_depth,
                    batch_window=cfg.ingest_batch_window,
                    batch_linger_ms=cfg.ingest_batch_linger_ms,
                )
                # /statusz occupancy section (binary_utils health
                # listener): in-flight uploads vs the admission bound
                from ..statusz import register_status_provider

                pipe = self._ingest

                def _ingest_status(pipe=pipe, cfg=cfg):
                    inflight, bound = pipe.depth()
                    return {
                        "inflight": inflight,
                        "queue_depth_bound": bound,
                        "occupancy": round(inflight / bound, 3) if bound else 0.0,
                        "decrypt_workers": pipe.decrypt_workers,
                        "decode_workers": pipe.decode_workers,
                        "batch_window": pipe.batch_window,
                        "batch_linger_ms": pipe.batch_linger_s * 1000.0,
                        "queue_high_watermark": cfg.queue_high_watermark,
                    }

                register_status_provider("ingest", _ingest_status)
            if self._admission is None:
                cfg = self.agg.cfg
                self._admission = AdmissionController(
                    AdmissionConfig(
                        upload_bucket_rate=cfg.upload_bucket_rate,
                        upload_bucket_burst=cfg.upload_bucket_burst,
                        aggregate_bucket_rate=cfg.aggregate_bucket_rate,
                        aggregate_bucket_burst=cfg.aggregate_bucket_burst,
                        shed_priority=tuple(cfg.shed_priority),
                        queue_high_watermark=cfg.queue_high_watermark,
                        shed_retry_after_s=cfg.upload_shed_retry_after_s,
                    ),
                    depth_fn=self._ingest.depth,
                    # degraded-mode serving: aggregate-step routes shed
                    # 503 while the datastore supervisor is not up
                    # (uploads keep flowing into the spill journal)
                    supervisor_fn=lambda: getattr(self.agg.ds, "supervisor", None),
                )
            return self._ingest, self._admission

    def close(self) -> None:
        """Drain the ingest pipeline's worker threads (shutdown)."""
        with self._ingest_lock:
            ingest = self._ingest
        if ingest is not None:
            ingest.close()

    def _taskprov_config(self, task_id: TaskId, headers):
        """Decode + verify the dap-taskprov header (reference
        http_handlers.rs:575-607 parse_taskprov_header): the taskprov
        task ID must equal SHA-256 of the encoded TaskConfig."""
        if not self.agg.cfg.taskprov_enabled:
            return None
        from ..messages.taskprov import TASKPROV_HEADER, TaskConfig

        lowered = {k.lower(): v for k, v in headers.items()}
        raw = lowered.get(TASKPROV_HEADER)
        if raw is None:
            return None
        try:
            encoded = base64.urlsafe_b64decode(raw + "=" * (-len(raw) % 4))
        except Exception:
            raise InvalidMessage("taskprov header could not be decoded", task_id)
        import hashlib

        if hashlib.sha256(encoded).digest() != task_id.data:
            raise InvalidMessage(
                "derived taskprov task ID does not match task config", task_id
            )
        return TaskConfig.from_bytes(encoded)

    def _check_helper_auth(self, ta, task_id, headers, taskprov_config):
        """Aggregator (leader->helper) auth: taskprov peer tokens when
        the header is present, per-task token otherwise
        (reference aggregator.rs:420-432)."""
        if taskprov_config is not None:
            self.agg.taskprov_authorize_request(Role.LEADER, task_id, taskprov_config, headers)
        else:
            self.agg.check_aggregator_auth(ta.task, headers)

    def handle(self, method: str, path: str, query: dict, headers, body: bytes):
        """-> (status, content_type, body_bytes, extra_headers). Wraps
        _handle (whose handlers may return 3- or 4-tuples) with the
        per-route request counter/latency histogram (the analog of the
        reference's per-status metrics, http_handlers.rs:266)."""
        from time import monotonic

        from .. import metrics

        from ..trace import adopt_traceparent, current_context, reset_traceparent, span

        route = "none"
        for m, rx, name in _ROUTES:
            if m == method and rx.match(path):
                route = name
                break
        start = monotonic()
        # adopt the caller's trace (leader -> helper propagation): one
        # trace then stitches upload -> init -> continue across both
        # aggregators (reference trace.rs:44-90 OTLP layer analog)
        tp_token = adopt_traceparent(
            next((v for k, v in headers.items() if k.lower() == "traceparent"), None)
        )
        exemplar_ctx = None
        try:
            with span(f"dap.{route}", method=method):
                # the request span's trace id becomes the latency
                # histogram sample's exemplar (the span itself has
                # already reset its context by observation time below)
                exemplar_ctx = current_context()
                result = self._handle(method, path, query, headers, body)
        finally:
            reset_traceparent(tp_token)
        metrics.http_request_duration.observe(
            monotonic() - start,
            exemplar_trace_id=exemplar_ctx[0] if exemplar_ctx else None,
            route=route,
        )
        metrics.http_request_counter.add(route=route, status=str(result[0]))
        if len(result) == 3:
            result = result + ({},)
        return result

    def _handle(self, method: str, path: str, query: dict, headers, body: bytes):
        try:
            if method == "OPTIONS":
                if _cors_allow(path) is not None:
                    return 204, "text/plain", b""
                return 404, "text/plain", b"not found"
            for m, rx, name in _ROUTES:
                if m != method:
                    continue
                match = rx.match(path)
                if match:
                    want = _request_media_types().get(name)
                    if want is not None:
                        got = {k.lower(): v for k, v in headers.items()}.get(
                            "content-type", ""
                        )
                        # Exact match, no parameter stripping — the
                        # reference's validate_content_type requires the
                        # precise media type and answers 400 BadRequest
                        # (http_handlers.rs validate_content_type).
                        if got != want:
                            from ..messages.problem_type import DapProblemType

                            doc = DapProblemType.INVALID_MESSAGE.document(
                                detail=f"unexpected media type: {got!r} (want {want!r})"
                            )
                            return (
                                400,
                                "application/problem+json",
                                json.dumps(doc).encode(),
                            )
                    route_class = _ROUTE_CLASS.get(name)
                    if route_class is not None:
                        # shed BEFORE any decode/crypto/datastore work:
                        # the whole point of admission control is that a
                        # refused request costs ~nothing. The leader's
                        # propagated budget (DAP-Janus-Deadline,
                        # backdated by the request's accept-queue wait)
                        # is an admission signal too: already-dead work
                        # sheds 503 here instead of burning HPKE.
                        from ..core import deadline as deadline_mod

                        dl = deadline_mod.parse_header(
                            headers,
                            queue_age_s=deadline_mod.request_queue_age(),
                        )
                        _, admission = self._ensure_ingest()
                        admission.admit(route_class, deadline=dl)
                        # thread the budget through the handler: the
                        # decrypt loop / pre-tx checks raise
                        # DeadlineExceeded (mapped to the conclusive
                        # 408 below) and the engine watchdog bounds the
                        # device dispatch with it
                        with deadline_mod.deadline_scope(dl):
                            return getattr(self, "h_" + name)(
                                match, query, headers, body
                            )
                    return getattr(self, "h_" + name)(match, query, headers, body)
            return 404, "text/plain", b"not found"
        except ShedError as e:
            # 429 for capacity sheds, 503 for availability sheds
            # (datastore down / journal full) — both with Retry-After
            from .. import metrics

            status = getattr(e, "status", 429)
            metrics.upload_shed_counter.add(route=e.route_class, reason=e.reason)
            doc = {
                "type": "about:blank",
                "status": status,
                "detail": str(e),
            }
            return (
                status,
                "application/problem+json",
                json.dumps(doc).encode(),
                {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))},
            )
        except DeadlineExceeded as e:
            # the caller's budget died mid-handler (decrypt loop,
            # watchdog-bounded engine, pre-commit check): answer the
            # CONCLUSIVE deadline status — not a retryable 5xx — so the
            # leader steps back instead of re-sending dead work
            # (docs/ROBUSTNESS.md "Device hangs & deadlines")
            doc = {
                "type": "about:blank",
                "status": DEADLINE_EXCEEDED_STATUS,
                "detail": f"request deadline exceeded: {e}",
            }
            return (
                DEADLINE_EXCEEDED_STATUS,
                "application/problem+json",
                json.dumps(doc).encode(),
            )
        except AggregatorError as e:
            doc = e.problem_document()
            if doc is None:
                log.exception("internal aggregator error")
                return 500, "text/plain", str(e).encode()
            return (
                e.status,
                "application/problem+json",
                json.dumps(doc).encode(),
            )
        except DecodeError as e:
            # codec failures are invalidMessage problem documents
            # (reference error.rs maps Error::MessageDecode)
            from ..messages.problem_type import DapProblemType

            doc = DapProblemType.INVALID_MESSAGE.document(detail=f"undecodable request: {e}")
            return 400, "application/problem+json", json.dumps(doc).encode()
        except Exception:
            log.exception("unhandled error in DAP handler")
            return 500, "text/plain", b"internal error"

    # --- handlers ---
    def h_hpke_config(self, match, query, headers, body):
        from ..messages import HpkeConfigList

        tid = query.get("task_id")
        if tid is None:
            raise InvalidMessage("task_id query parameter required")
        task_id = TaskId(_b64dec(tid, 32))
        try:
            ta = self.agg.task_aggregator_for(task_id)
            configs = ta.hpke_config_list()
            if not configs.configs:
                raise UnrecognizedTask("no per-task keys", task_id)
        except UnrecognizedTask:
            # taskprov tasks aren't locally provisioned at upload time and
            # carry no per-task keys: advertise the global keys instead
            # (reference aggregator.rs:276-280)
            globals_ = self.agg.global_hpke_keypairs.configs()
            if not (self.agg.cfg.taskprov_enabled and globals_):
                raise
            configs = HpkeConfigList(tuple(globals_))
        return 200, "application/dap-hpke-config-list", configs.to_bytes()

    def h_upload(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        ta = self.agg.task_aggregator_for(task_id)
        # staged ingest: decode and HPKE-decrypt run on the pipeline's
        # bounded worker stages, the write lands in the
        # ReportWriteBatcher group commit; this thread parks on the
        # ticket so the response still means "durably written". Stage
        # errors (DecodeError, ReportRejected, ...) re-raise here and
        # map to problem documents exactly as the inline path did.
        ingest, _ = self._ensure_ingest()
        ticket = ingest.submit(ta, self.agg.clock, body)
        fresh = ticket.result()
        if not fresh:
            # replay is silent success (DAP-07 upload semantics)
            from .. import metrics

            metrics.upload_replay_counter.add()
        return 201, "text/plain", b""

    def h_aggregate_init(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        job_id = AggregationJobId(_b64dec(match.group(2), 16))
        taskprov_config = self._taskprov_config(task_id, headers)
        # helper endpoint: the provisioning peer is the leader
        ta = self.agg.task_aggregator_for(task_id, taskprov_config, headers, peer_role=Role.LEADER)
        self._check_helper_auth(ta, task_id, headers, taskprov_config)
        # XOF framing-version check: a leader/helper xof_mode mismatch
        # would otherwise silently reject every report (the two framings
        # produce disjoint pseudorandom streams, SECURITY-NOTES.md).
        # The leader advertises its mode; tolerate absence so a
        # spec-conformant non-janus leader can pair with a draft-mode
        # task.
        sent_mode = {k.lower(): v for k, v in headers.items()}.get(XOF_MODE_HEADER)
        task_mode = ta.task.vdaf.xof_mode
        if sent_mode is not None and sent_mode != task_mode:
            raise InvalidMessage(
                f"XOF framing mismatch: peer uses {sent_mode!r}, task is "
                f"{task_mode!r} — aggregators must deploy the same mode",
                task_id,
            )
        req = AggregationJobInitializeReq.from_bytes(body)
        resp = ta.handle_aggregate_init(self.agg.ds, self.agg.clock, job_id, req, body)
        return 200, "application/dap-aggregation-job-resp", resp.to_bytes()

    def h_aggregate_continue(self, match, query, headers, body):
        from ..messages import AggregationJobContinueReq

        task_id = TaskId(_b64dec(match.group(1), 32))
        job_id = AggregationJobId(_b64dec(match.group(2), 16))
        taskprov_config = self._taskprov_config(task_id, headers)
        ta = self.agg.task_aggregator_for(task_id)
        self._check_helper_auth(ta, task_id, headers, taskprov_config)
        req = AggregationJobContinueReq.from_bytes(body)
        resp = ta.handle_aggregate_continue(self.agg.ds, self.agg.clock, job_id, req, body)
        return 200, "application/dap-aggregation-job-resp", resp.to_bytes()

    def h_collection_create(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        cj_id = CollectionJobId(_b64dec(match.group(2), 16))
        ta = self.agg.task_aggregator_for(task_id)
        self.agg.check_collector_auth(ta.task, headers)
        req = CollectionReq.from_bytes(body)
        ta.handle_create_collection_job(self.agg.ds, cj_id, req)
        return 201, "text/plain", b""

    def h_collection_poll(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        cj_id = CollectionJobId(_b64dec(match.group(2), 16))
        ta = self.agg.task_aggregator_for(task_id)
        self.agg.check_collector_auth(ta.task, headers)
        ready, collection = ta.handle_get_collection_job(self.agg.ds, cj_id)
        if not ready:
            # advise the poll cadence (reference collector honors this,
            # collector/src/lib.rs:466; leader-side emission analog of
            # aggregator_api's job-poll hint)
            return 202, "text/plain", b"", {"Retry-After": str(self.agg.cfg.collection_retry_after_s)}
        return 200, "application/dap-collection", collection.to_bytes()

    def h_collection_delete(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        cj_id = CollectionJobId(_b64dec(match.group(2), 16))
        ta = self.agg.task_aggregator_for(task_id)
        self.agg.check_collector_auth(ta.task, headers)
        ta.handle_delete_collection_job(self.agg.ds, cj_id)
        return 204, "text/plain", b""

    def h_aggregate_share(self, match, query, headers, body):
        task_id = TaskId(_b64dec(match.group(1), 32))
        taskprov_config = self._taskprov_config(task_id, headers)
        # helper endpoint: allow taskprov re-provisioning here too (the
        # reference handles taskprov on aggregate_share, aggregator.rs:641)
        ta = self.agg.task_aggregator_for(task_id, taskprov_config, headers, peer_role=Role.LEADER)
        self._check_helper_auth(ta, task_id, headers, taskprov_config)
        req = AggregateShareReq.from_bytes(body)
        resp = ta.handle_aggregate_share(self.agg.ds, req)
        return 200, "application/dap-aggregate-share", resp.to_bytes()

    def h_ledger(self, match, query, headers, body):
        """Cross-aggregator reconciliation read (janus_tpu/ledger.py):
        this aggregator's per-batch aggregated report counts plus its
        lifecycle counters for the task, behind the same leader->helper
        aggregator auth as the DAP aggregation steps. The payload is
        the peer's half of the conservation comparison — the
        observability analog of a linear tag over the batch."""
        import json

        task_id = TaskId(_b64dec(match.group(1), 32))
        taskprov_config = self._taskprov_config(task_id, headers)
        ta = self.agg.task_aggregator_for(
            task_id, taskprov_config, headers, peer_role=Role.LEADER
        )
        self._check_helper_auth(ta, task_id, headers, taskprov_config)

        def read(tx):
            return tx.ledger_batch_counts(task_id), tx.get_task_counters(task_id)

        batch_counts, counters = self.agg.ds.run_tx(read, "ledger_peer_read")
        doc = {"batch_counts": batch_counts, "counters": counters}
        return 200, "application/json", json.dumps(doc, sort_keys=True).encode()


class DapServer:
    """Bounded-concurrency HTTP server hosting a DapHttpApp (+ /healthz).

    Requests are served by a fixed pool of `max_handler_threads`
    workers (BoundedThreadingHTTPServer) instead of a thread per
    connection: a burst above capacity waits in the accept backlog or
    is shed by the admission controller with 429, and handler thread
    count stays ≤ the bound no matter the connection count."""

    def __init__(
        self,
        app: DapHttpApp,
        host: str = "127.0.0.1",
        port: int = 0,
        max_handler_threads: int | None = None,
    ):
        outer = self
        if max_handler_threads is None:
            try:
                max_handler_threads = int(app.agg.cfg.max_handler_threads)
            except Exception:  # test doubles without a real Aggregator
                max_handler_threads = 32

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # an idle keep-alive connection must not pin a pool worker
            # forever: time out the blocking request read
            timeout = 60

            def _dispatch(self, method):
                from urllib.parse import parse_qsl, urlsplit

                from ..core import deadline as deadline_mod

                parts = urlsplit(self.path)
                if parts.path == "/healthz":
                    self._reply(200, "text/plain", b"ok")
                    return
                # charge the accept-queue wait against this request's
                # propagated deadline (stamped at accept by
                # BoundedThreadingHTTPServer.queue_age_s; consumed on
                # read, so later keep-alive requests — parsed the
                # instant they arrive, their wait is the CLIENT's idle
                # time — read age 0)
                age_fn = getattr(self.server, "queue_age_s", None)
                age = age_fn(self.request) if age_fn is not None else None
                deadline_mod.set_request_queue_age(age or 0.0)
                query = dict(parse_qsl(parts.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    status, ctype, out, extra = outer.app.handle(
                        method, parts.path, query, dict(self.headers.items()), body
                    )
                except Exception:
                    # a handler bug must answer 500, not kill the
                    # keep-alive connection mid-request (the client sees
                    # an opaque ECONNRESET otherwise — found by the
                    # shell-capacity bench at 16-way upload)
                    log.exception("unhandled error serving %s %s", method, parts.path)
                    status, ctype, out, extra = (
                        500,
                        "application/problem+json",
                        b'{"type":"about:blank","status":500}',
                        None,
                    )
                self._reply(status, ctype, out, method, extra)

            def _reply(self, status, ctype, out, method="GET", extra=None):
                from urllib.parse import urlsplit

                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                if getattr(self.server, "saturated", False):
                    # pool full: finish this response, then recycle the
                    # connection so parked keep-alive clients can't pin
                    # every worker and starve new connections
                    self.send_header("Connection", "close")
                    self.close_connection = True
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                # CORS only on browser-reachable routes (reference
                # http_handlers.rs:236-259 scopes CORS to hpke_config,
                # upload, and collection_jobs; aggregator-to-aggregator
                # endpoints get none)
                allow = _cors_allow(urlsplit(self.path).path)
                if allow is not None:
                    self.send_header("Access-Control-Allow-Origin", "*")
                    if method == "OPTIONS":
                        self.send_header("Access-Control-Allow-Methods", allow)
                        self.send_header(
                            "Access-Control-Allow-Headers",
                            "content-type, authorization, dap-auth-token",
                        )
                self.end_headers()
                if out:
                    self.wfile.write(out)

            def do_GET(self):
                self._dispatch("GET")

            def do_OPTIONS(self):
                self._dispatch("OPTIONS")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, fmt, *args):
                log.debug("http: " + fmt, *args)

        self.app = app

        from ..binary_utils import BoundedThreadingHTTPServer

        self.server = BoundedThreadingHTTPServer(
            (host, port), Handler, max_handler_threads=max_handler_threads
        )
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}/"

    def start(self) -> "DapServer":
        self._thread = threading.Thread(
            target=self.server.serve_forever, name="dap-listener", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if isinstance(self.app, DapHttpApp):  # not for routing test doubles
            self.app.close()
