"""Serialized-executable AOT cache (ISSUE 14; docs/ARCHITECTURE.md
"Cold-start and prewarm").

The persistent XLA compile cache only skips the *XLA compile*; a
restarted process still pays Python tracing + lowering per jit
specialization, which measures 3-6 s per histogram-class program on
the CPU bench — most of a warm boot. This layer closes that gap: the
first cold dispatch of a specialization compiles through jax's AOT
path (`jit.lower(args).compile()`), SERIALIZES the compiled executable
(`jax.experimental.serialize_executable`) to disk, and every later
process — the boot prewarm, a restarted driver, a canary rebuild —
deserializes it in ~tens-to-hundreds of milliseconds with no trace at
all. Deserialized executables are the same compiled bytes, so results
are bit-identical by construction (pinned by test).

Keying: blobs are named by a digest over (jax version, backend
platform + device count, the HOST target-machine fingerprint — CPU
feature flags, see below — the engine identity — vdaf config + a
verify key digest, since single-task programs close over the key as a
trace constant — the jit variant name, the mesh geometry
`(dp, sp, device count)` for mesh programs, and the argument avals
(shape + dtype tree)). Anything the digest misses — a jax upgrade
changing the wire format, a corrupted blob — surfaces as a
deserialization error: the blob is deleted and the call falls back to
the plain jit, so the cache can only ever cost a cold compile, never
correctness.

Cross-machine poison (MULTICHIP_r05, rc 124): XLA:CPU AOT executables
embed the COMPILE machine's CPU features ("Target machine feature
+prefer-no-gather is not supported on the host machine"), and a blob
compiled elsewhere could stall the loader rather than raise cleanly.
Two defenses: the host fingerprint in the digest means a foreign blob
is never even looked up, and each blob carries the writer's
fingerprint, checked BEFORE the native deserialize — a mismatch
deletes the blob and falls back to the jit without ever entering the
loader.

Scope: single-device AND mesh jits (mesh digests carry their
(dp, sp, device count) geometry, so a blob only loads on its own
topology), and only while ARMED (janus_main arms it next to the
compile cache; bare tests/bench processes see byte-identical behavior
to before).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading

log = logging.getLogger(__name__)

_lock = threading.Lock()
# serializes every AOT serialization compile: the XLA-compilation-cache
# disable below mutates process-global jax config
_compile_flag_lock = threading.Lock()
_ARMED: dict = {"dir": None}
_STATS = {"loads": 0, "saves": 0, "errors": 0, "bytes_saved": 0}

BLOB_SUFFIX = ".jaxexe"
# disk bound: a production deployment's distinct specializations are
# few (O(ops x buckets x tasks) with STABLE verify keys), but test/
# chaos harnesses mint random keys per run, so a shared cache dir
# accumulates dead blobs — at the cap, saves trim the oldest-mtime
# blobs first (dead keys age out, live ones stay warm)
MAX_BLOBS = 256


def arm(directory: str) -> None:
    """Enable the AOT executable cache at `directory` (created
    lazily). janus_main calls this beside enable_compile_cache."""
    with _lock:
        _ARMED["dir"] = os.path.expanduser(directory)


def disarm() -> None:
    with _lock:
        _ARMED["dir"] = None


def armed_dir() -> str | None:
    return _ARMED["dir"]


def stats() -> dict:
    """O(1) counter snapshot (no directory scan) — the prewarm loop
    diffs this per warmed entry."""
    with _lock:
        return dict(_STATS)


def status() -> dict:
    """The `aot` slice of the /statusz engine_prewarm section."""
    d = _ARMED["dir"]
    blobs = blob_bytes = 0
    if d:
        try:
            with os.scandir(d) as it:
                for ent in it:
                    if ent.name.endswith(BLOB_SUFFIX):
                        blobs += 1
                        try:
                            blob_bytes += ent.stat().st_size
                        except OSError:
                            pass
        except OSError:
            pass
    with _lock:
        stats = dict(_STATS)
    return {"enabled": d is not None, "dir": d, "blobs": blobs, "blob_bytes": blob_bytes, **stats}


def reset_for_tests() -> None:
    with _lock:
        _ARMED["dir"] = None
        _STATS.update(loads=0, saves=0, errors=0, bytes_saved=0)


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _STATS[key] = _STATS.get(key, 0) + n


def _leaf_sig(x) -> str:
    if x is None:
        return "N"
    if isinstance(x, (bytes, bool, int, float)):
        return repr(x)[:64]
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        raise TypeError(f"unsupported AOT arg leaf {type(x).__name__}")
    return f"{tuple(shape)}:{dtype}"


def _args_sig(args) -> str:
    parts = []
    for a in args:
        if isinstance(a, (tuple, list)):
            parts.append("(" + ",".join(_args_sig((x,)) for x in a) + ")")
        else:
            parts.append(_leaf_sig(a))
    return "|".join(parts)


_HOST_FP: str | None = None


def host_fingerprint() -> str:
    """Digest of the host's target-machine identity: architecture plus
    the CPU feature flags XLA:CPU bakes into AOT executables. Part of
    every blob digest AND stored inside each blob (checked before the
    native deserialize) — the MULTICHIP_r05 cross-machine poison fix."""
    global _HOST_FP
    if _HOST_FP is None:
        import platform

        parts = [platform.system(), platform.machine()]
        flags = ""
        try:
            with open("/proc/cpuinfo", encoding="utf-8", errors="replace") as f:
                for line in f:
                    # x86 "flags", arm64 "Features" — first hit is the
                    # boot CPU; features are uniform across cores on
                    # the machines we serve from
                    if line.lower().startswith(("flags", "features")):
                        flags = " ".join(sorted(line.split(":", 1)[1].split()))
                        break
        except OSError:
            flags = platform.processor() or ""
        parts.append(flags)
        _HOST_FP = hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]
    return _HOST_FP


def engine_base(
    inst_dict: dict,
    verify_key: bytes,
    name: str,
    mesh: tuple[int, int, int] | None = None,
) -> str:
    """Digest base identifying one engine's jit variant across
    processes (see the module docstring for what it must cover).
    `mesh` is the (dp, sp, device count) geometry for mesh programs —
    a blob must only ever load on its own topology."""
    import json

    import jax

    return "|".join(
        (
            jax.__version__,
            jax.default_backend(),
            str(len(jax.local_devices())),
            host_fingerprint(),
            json.dumps(inst_dict, sort_keys=True, separators=(",", ":")),
            hashlib.sha256(verify_key).hexdigest()[:16],
            name,
            "mesh:%dx%d/%d" % mesh if mesh is not None else "single",
        )
    )


class AotJit:
    """Wraps one engine jit: per argument-aval specialization, load a
    serialized executable if one exists, else compile via the AOT path
    and serialize it for the next process. Falls back to the wrapped
    jit on ANY cache trouble — including a blob that deserializes but
    faults on its first execution."""

    __slots__ = ("_jitted", "_base", "_loaded", "_lock", "_sig_locks")

    def __init__(self, jitted, base: str):
        self._jitted = jitted
        self._base = base
        self._loaded: dict[str, object] = {}
        self._lock = threading.Lock()
        # per-signature first-call locks: concurrent first callers of
        # the SAME specialization must not duplicate a multi-second
        # compile, but a different specialization's ~tens-of-ms blob
        # load must never queue behind one either
        self._sig_locks: dict[str, threading.Lock] = {}

    def _blob_path(self, d: str, sig: str) -> str:
        h = hashlib.sha256(f"{self._base}||{sig}".encode()).hexdigest()
        return os.path.join(d, h + BLOB_SUFFIX)

    def _drop_and_fall_back(self, sig: str, path: str | None, args):
        _bump("errors")
        self._loaded.pop(sig, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
        return self._jitted(*args)

    def __call__(self, *args):
        d = _ARMED["dir"]
        if d is None:
            return self._jitted(*args)
        try:
            sig = _args_sig(args)
        except TypeError:
            return self._jitted(*args)
        comp = self._loaded.get(sig)
        if comp is not None:
            try:
                return comp(*args)
            except Exception:
                # aval drift / runtime rejection: drop to the jit,
                # which re-specializes freely
                return self._drop_and_fall_back(sig, None, args)
        with self._lock:
            sig_lock = self._sig_locks.setdefault(sig, threading.Lock())
        path = self._blob_path(d, sig)
        loaded_from_disk = False
        with sig_lock:
            comp = self._loaded.get(sig)
            if comp is None:
                comp = self._try_load(path)
                loaded_from_disk = comp is not None
                if comp is None:
                    comp = self._compile_and_save(path, args)
                if comp is None:
                    return self._jitted(*args)
                self._loaded[sig] = comp
        if not loaded_from_disk:
            return comp(*args)
        try:
            return comp(*args)
        except Exception:
            # the first execution of a DESERIALIZED executable is the
            # last place a bad blob can surface (the digest + envelope
            # fingerprint catch cross-machine blobs up front, but a
            # same-machine blob can still be stale or corrupt): it
            # must cost a recompile, never a failed serving dispatch
            log.warning(
                "AOT blob %s loaded but faulted on first execution; "
                "deleting and falling back to the jit", path, exc_info=True,
            )
            return self._drop_and_fall_back(sig, path, args)

    def _try_load(self, path: str):
        from jax.experimental import serialize_executable

        try:
            with open(path, "rb") as f:
                blob = pickle.loads(f.read())
            # v2 blob envelope: the writer's host fingerprint rides
            # along and is checked BEFORE the native deserialize — a
            # foreign-machine executable must fall back here, not
            # stall inside the XLA:CPU loader (MULTICHIP_r05). A
            # legacy 3-tuple blob has no fingerprint: treat it as
            # foreign (its digest scheme is gone anyway).
            if not (isinstance(blob, dict) and blob.get("v") == 2):
                raise ValueError("legacy AOT blob envelope (no fingerprint)")
            if blob.get("fp") != host_fingerprint():
                raise ValueError(
                    f"AOT blob compiled on another machine "
                    f"(fp {blob.get('fp')!r} != host {host_fingerprint()!r})"
                )
            serialized, in_tree, out_tree = blob["payload"]
        except FileNotFoundError:
            return None
        except Exception:
            _bump("errors")
            log.warning("AOT blob %s unreadable; deleting", path, exc_info=True)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            comp = serialize_executable.deserialize_and_load(
                serialized, in_tree, out_tree
            )
        except Exception:
            # bad blob: jax/XLA version skew, or a blob serialized
            # from an XLA-persistent-cache-HIT executable ("Symbols
            # not found" — such executables carry no JIT object code;
            # _compile_and_save forces a real compile to prevent this,
            # but blobs written before that fix may linger). Delete and
            # recompile — the cache can only cost a compile, never
            # correctness.
            _bump("errors")
            log.warning(
                "AOT blob %s failed to deserialize; deleting and recompiling",
                path, exc_info=True,
            )
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        _bump("loads")
        return comp

    def _compile_and_save(self, path: str, args):
        import jax
        from jax.experimental import serialize_executable

        try:
            # the serialization compile must be a REAL compile: an
            # executable loaded from the XLA persistent cache carries
            # no JIT object code, and serializing one yields a blob
            # that fails every later deserialize with "Symbols not
            # found" (pinned by test). The AOT blob supersedes the XLA
            # cache for this program anyway. The flag is process-GLOBAL
            # jax config — the module lock keeps a concurrent wrapper's
            # compile from racing the disable/restore window and
            # serializing a cache-hit (poisoned) executable. Accepted
            # tradeoff: an UNRELATED first compile on another thread
            # that lands inside the window skips the persistent cache
            # once and recompiles on the next restart — rare
            # (concurrent first-compiles only; mesh programs all
            # compile on the single dispatch lane, so they can't race
            # each other), self-limited, and never a correctness issue.
            with _compile_flag_lock:
                cache_was_on = bool(jax.config.jax_enable_compilation_cache)
                if cache_was_on:
                    jax.config.update("jax_enable_compilation_cache", False)
                try:
                    comp = self._jitted.lower(*args).compile()
                finally:
                    if cache_was_on:
                        jax.config.update("jax_enable_compilation_cache", True)
        except Exception:
            _bump("errors")
            return None  # caller falls back to the jit call path
        try:
            d = os.path.dirname(path)
            os.makedirs(d, exist_ok=True)
            if os.path.exists(path):
                # an in-process load fallback kept a valid blob for
                # the next restart; don't churn it
                return comp
            with os.scandir(d) as it:
                blobs = [
                    (e.stat().st_mtime, e.path)
                    for e in it
                    if e.name.endswith(BLOB_SUFFIX)
                ]
            # at the disk bound, age out the oldest blobs (dead test
            # keys) instead of refusing to cache the live one
            for _, old in sorted(blobs)[: max(0, len(blobs) - (MAX_BLOBS - 1))]:
                try:
                    os.unlink(old)
                except OSError:
                    pass
            blob = pickle.dumps(
                {
                    "v": 2,
                    "fp": host_fingerprint(),
                    "payload": serialize_executable.serialize(comp),
                }
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            _bump("saves")
            _bump("bytes_saved", len(blob))
        except Exception:
            _bump("errors")
            log.warning("AOT blob save to %s failed", path, exc_info=True)
        return comp


def wrap(jitted, base: str):
    """Wrap a plain jax.jit callable for the AOT cache. Always wraps —
    the wrapper is a no-op passthrough while disarmed — so an engine
    built before janus_main arms the cache still benefits."""
    return AotJit(jitted, base)
