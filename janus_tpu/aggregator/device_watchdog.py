"""Watchdog-supervised device dispatch (docs/ROBUSTNESS.md "Device
hangs & deadlines").

`jax.block_until_ready` / a device fetch has no timeout: a wedged XLA
dispatch (device hang, tunnel stall) parks the calling thread forever,
silently holding a job lease until TTL while the work it was doing is
already dead. The accelerator must be treated as a failable peer —
exactly like the helper behind the outbound circuit breaker.

`DispatchWatchdog.run(fn, deadline=...)` executes the device-touching
closure on a reusable worker thread and waits at most until the
caller's deadline (the ambient `core.deadline` budget: a job driver's
lease bound, a helper handler's propagated request deadline). On
expiry the dispatch is **abandoned**: the worker thread stays parked on
the hung device call (it cannot be interrupted — that is the point),
is counted in `janus_hung_dispatches_total` and the
`janus_abandoned_dispatch_threads` gauge, shows up in the /statusz
`device_watchdog` section WITH its current stack, and the caller gets
`DeviceHangError` — which the engine turns into a quarantine and the
job drivers turn into a step-back.

Abandoned threads are a leak by design (each pins a stack and whatever
device buffers its call staged), so they are capped: at
`abandoned_thread_cap` parked threads the watchdog trips **host-only
mode** — every EngineCache serves from the scalar host engine and no
further device dispatches are attempted — because a device that has
eaten that many threads is not coming back on its own.

Disarmed cost (no ambient deadline — tests, bench, uploads): one
contextvar read and a None check, measured by the bench --dry-run
`watchdog_overhead` record (≤ 1 µs/dispatch acceptance bound).
"""

from __future__ import annotations

import contextvars
import logging
import os
import sys
import threading
import time

from ..core.deadline import DeadlineExceeded, current_deadline

log = logging.getLogger(__name__)


class DeviceHangError(RuntimeError):
    """A supervised device dispatch exceeded its deadline and was
    abandoned. NOT an OOM: the engine's OOM ladder must not absorb it —
    it quarantines the engine and the job steps back instead."""

    def __init__(self, label: str, waited_s: float):
        super().__init__(
            f"device dispatch {label!r} abandoned after {waited_s:.3f}s "
            "(deadline exceeded; thread parked and counted)"
        )
        self.label = label
        self.waited_s = waited_s


# marks code already running ON a watchdog worker so nested supervised
# regions (chunked dispatch recursion) don't stack a second worker
_in_watchdog: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "janus_in_watchdog", default=False
)


class _Job:
    __slots__ = ("fn", "ctx", "done", "result", "exc", "lock", "abandoned", "label", "started_at")

    def __init__(self, fn, ctx, label: str):
        self.fn = fn
        self.ctx = ctx
        self.done = threading.Event()
        self.result = None
        self.exc: BaseException | None = None
        self.lock = threading.Lock()
        self.abandoned = False
        self.label = label
        self.started_at = time.monotonic()


class DispatchWatchdog:
    """One per process (module-level WATCHDOG below); engines call
    through `run`."""

    def __init__(self, abandoned_thread_cap: int = 8):
        self.abandoned_thread_cap = max(1, abandoned_thread_cap)
        self._lock = threading.Lock()
        self._idle: list = []  # idle (thread, job queue) pairs
        self._stalled: dict[int, dict] = {}  # thread ident -> info
        self._host_only = False
        self._hung_total = 0
        self._seq = 0

    # ------------------------------------------------------------------
    def host_only(self) -> bool:
        """True once the abandoned-thread cap tripped: no further
        device dispatches; engines serve from the host engine."""
        return self._host_only

    def reset_for_tests(self) -> None:
        """Drop host-only mode and forget stalled bookkeeping (parked
        threads themselves are daemons and unwind on their own)."""
        from .. import metrics

        with self._lock:
            self._host_only = False
            self._stalled.clear()
            self._idle.clear()
        metrics.abandoned_dispatch_threads.set(0.0)

    # ------------------------------------------------------------------
    def _worker_loop(self, q) -> None:
        from .. import metrics

        while True:
            job: _Job = q.get()
            try:
                result = job.ctx.run(job.fn)
                exc = None
            except BaseException as e:  # noqa: BLE001 - crosses threads
                result, exc = None, e
            ident = threading.get_ident()
            with job.lock:
                job.result, job.exc = result, exc
                abandoned = job.abandoned
                job.done.set()
            if abandoned:
                # the hung call finally returned (device recovered or
                # process unwinding): result discarded, thread retires
                with self._lock:
                    self._stalled.pop(ident, None)
                    n = len(self._stalled)
                metrics.abandoned_dispatch_threads.set(float(n))
                log.warning(
                    "abandoned dispatch %s completed after %.1fs; worker retiring",
                    job.label, time.monotonic() - job.started_at,
                )
                return
            with self._lock:
                self._idle.append((threading.current_thread(), q))

    def _checkout_worker(self):
        import queue

        with self._lock:
            if self._idle:
                return self._idle.pop()
            self._seq += 1
            seq = self._seq
        q: queue.Queue = queue.Queue(maxsize=1)
        t = threading.Thread(
            target=self._worker_loop, args=(q,), name=f"device-watchdog-{seq}", daemon=True
        )
        t.start()
        return t, q

    # ------------------------------------------------------------------
    def run(self, fn, *, deadline: float | None = None, label: str = "dispatch",
            vdaf: str = "", on_hang=None):
        """Execute `fn` under supervision.

        deadline None (or already inside a watchdog worker) = direct
        call: the disarmed path must cost nothing. Otherwise `fn` runs
        on a worker with the caller's context (trace/deadline
        contextvars propagate); past the deadline the worker is
        abandoned, `on_hang(label)` fires (the engine's quarantine
        hook) and DeviceHangError raises."""
        if deadline is None or _in_watchdog.get():
            return fn()
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise DeadlineExceeded(f"no budget left before dispatch {label!r}")
        if self._host_only:
            # engines check host_only() before dispatching; this is the
            # backstop for races around the trip
            raise DeviceHangError(label, 0.0)
        ctx = contextvars.copy_context()
        ctx.run(_in_watchdog.set, True)
        job = _Job(fn, ctx, label)
        thread, q = self._checkout_worker()
        q.put(job)
        if job.done.wait(remaining):
            if job.exc is not None:
                raise job.exc
            return job.result
        with job.lock:
            if job.done.is_set():
                # completed in the race window: not a hang
                if job.exc is not None:
                    raise job.exc
                return job.result
            job.abandoned = True
        waited = time.monotonic() - job.started_at
        self._record_hang(thread, job, vdaf, waited)
        if on_hang is not None:
            try:
                on_hang(label)
            except Exception:
                log.exception("watchdog on_hang hook failed for %s", label)
        raise DeviceHangError(label, waited)

    def _record_hang(self, thread: threading.Thread, job: _Job, vdaf: str, waited: float) -> None:
        from .. import metrics

        metrics.hung_dispatches_total.add(vdaf=vdaf, op=job.label)
        with self._lock:
            self._stalled[thread.ident] = {
                "label": job.label,
                "vdaf": vdaf,
                "thread": thread.name,
                "since": time.time(),
                "started_monotonic": job.started_at,
            }
            n = len(self._stalled)
            tripped = n >= self.abandoned_thread_cap and not self._host_only
            if tripped:
                self._host_only = True
        metrics.abandoned_dispatch_threads.set(float(n))
        self._hung_total += 1
        log.error(
            "device dispatch %s HUNG (%.3fs past its budget window); thread %s "
            "abandoned (%d/%d parked)",
            job.label, waited, thread.name, n, self.abandoned_thread_cap,
        )
        if tripped:
            log.error(
                "abandoned-dispatch cap %d reached: tripping HOST-ONLY mode — "
                "no further device dispatches this process",
                self.abandoned_thread_cap,
            )

    def drain(self, timeout_s: float = 2.0) -> bool:
        """Wait (bounded) for abandoned workers to retire — the process
        shutdown hook, called AFTER failpoints.release_hangs(): a
        daemon worker re-entering native device code while the
        interpreter finalizes segfaults the runtime, so give the woken
        workers a moment to unwind first. True when none remain."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._stalled:
                    return True
            time.sleep(0.01)
        with self._lock:
            return not self._stalled

    # ------------------------------------------------------------------
    def status(self) -> dict:
        """/statusz `device_watchdog` section: counts, host-only flag,
        and a live STACK DUMP of every parked (stalled) thread — the
        first thing an operator wants when a dispatch wedges. The dump
        uses the continuous profiler's shared frame formatter
        (profiler.format_stack), so this rendering and the
        /debug/profile folded stacks cannot diverge."""
        from ..profiler import format_stack

        with self._lock:
            stalled = {ident: dict(info) for ident, info in self._stalled.items()}
            host_only = self._host_only
            hung_total = self._hung_total
        frames = sys._current_frames()
        out_stalled = []
        now = time.monotonic()
        for ident, info in sorted(stalled.items()):
            ent = {
                "label": info["label"],
                "vdaf": info["vdaf"],
                "thread": info["thread"],
                "age_s": round(now - info["started_monotonic"], 3),
            }
            frame = frames.get(ident)
            if frame is not None:
                ent["stack"] = format_stack(frame, limit=12, lineno=True)
            out_stalled.append(ent)
        return {
            "abandoned_threads": len(stalled),
            "abandoned_thread_cap": self.abandoned_thread_cap,
            "host_only": host_only,
            "hung_dispatches_total": hung_total,
            "stalled": out_stalled,
        }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


WATCHDOG = DispatchWatchdog(
    abandoned_thread_cap=_env_int("JANUS_WATCHDOG_ABANDONED_CAP", 8)
)


def configure(abandoned_thread_cap: int | None = None) -> None:
    """Apply the YAML `device_watchdog:` knobs (janus_main); the
    JANUS_WATCHDOG_ABANDONED_CAP env var set the boot default."""
    if abandoned_thread_cap is not None:
        WATCHDOG.abandoned_thread_cap = max(1, int(abandoned_thread_cap))


def supervised(fn, *, label: str, vdaf: str = "", on_hang=None):
    """Module-level convenience: run `fn` under the process watchdog
    with the AMBIENT deadline (core.deadline contextvar). No deadline
    = direct call."""
    return WATCHDOG.run(
        fn, deadline=current_deadline(), label=label, vdaf=vdaf, on_hang=on_hang
    )


from ..statusz import register_status_provider as _register_status_provider

_register_status_provider("device_watchdog", WATCHDOG.status)
