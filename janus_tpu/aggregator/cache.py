"""Aggregator-side caches.

Equivalent of reference aggregator/src/cache.rs: the
`GlobalHpkeKeypairCache` (:24-139, refreshed in the background so every
request doesn't hit the datastore) and the `PeerAggregatorCache`
(:148, taskprov peers are read-heavy and practically immutable).
Refresh here is deadline-based on access rather than a background task:
cheap under the GIL and exactly as stale as the reference's timer.
"""

from __future__ import annotations

import threading
import time


class GlobalHpkeKeypairCache:
    """reference cache.rs:24. Serves decryption keypairs for config ids
    that are not bound to a single task (incl. all taskprov tasks)."""

    DEFAULT_REFRESH_INTERVAL_S = 30 * 60

    def __init__(self, ds, refresh_interval_s: float = DEFAULT_REFRESH_INTERVAL_S):
        self._ds = ds
        self._interval = refresh_interval_s
        self._lock = threading.Lock()
        self._by_id: dict[int, object] = {}
        self._configs: list = []
        self._next_refresh = 0.0
        self.refresh()

    def refresh(self) -> None:
        rows = self._ds.run_tx(lambda tx: tx.get_global_hpke_keypairs(), "global_hpke_refresh")
        with self._lock:
            self._by_id = {
                kp.config.id.id: kp for kp, state in rows if state in ("pending", "active")
            }
            self._configs = [kp.config for kp, state in rows if state == "active"]
            self._next_refresh = time.monotonic() + self._interval

    def _maybe_refresh(self) -> None:
        if time.monotonic() >= self._next_refresh:
            self.refresh()

    def keypair(self, config_id) -> object | None:
        """Decryption keypair for a config id (reference cache.rs:121;
        pending keys decrypt but aren't advertised)."""
        self._maybe_refresh()
        with self._lock:
            return self._by_id.get(getattr(config_id, "id", config_id))

    def configs(self) -> list:
        """Advertisable (active) configs (reference cache.rs:109)."""
        self._maybe_refresh()
        with self._lock:
            return list(self._configs)


class PeerAggregatorCache:
    """reference cache.rs:148: load-once cache of taskprov peers."""

    def __init__(self, ds):
        self._peers = ds.run_tx(
            lambda tx: tx.get_taskprov_peer_aggregators(), "peer_aggregator_load"
        )

    def get(self, endpoint: str, role):
        for peer in self._peers:
            if peer.endpoint == endpoint and peer.role == role:
                return peer
        return None
