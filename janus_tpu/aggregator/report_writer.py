"""Upload write batching.

Equivalent of reference aggregator/src/aggregator/report_writer.rs:24-165
(`ReportWriteBatcher`): buffer uploaded reports and flush them in a
single transaction, fanning the per-report outcome (fresh vs replayed)
back to each waiting upload request.

Flush policy is GROUP COMMIT, not a fixed timer: a dedicated flusher
thread writes whatever accumulated while the previous transaction ran.
A lone client therefore sees ~transaction latency (the reference's
`max_upload_batch_write_delay` default is 0, aggregator.rs:186-218),
while concurrent bursts batch naturally — the batch size adapts to
however many requests arrive per transaction. `max_write_delay_ms > 0`
adds an optional coalescing wait, capped by `max_batch_size`.

Datastore-outage survival (docs/ROBUSTNESS.md): with a journal
attached, a flush that hits a connection-class datastore error — or
that runs while the datastore supervisor reports the database not up,
or after a commit exceeded `spill_latency_s` — spills the batch to the
durable on-disk journal instead, and every waiter resolves fresh=True
(201 on the strength of the journal fsync). The journal's replayer
drains back through `flush_direct` on recovery; report-id dedup makes
that exactly-once. With no journal (the default) the flush path is
byte-identical to before — no new fsyncs, no new branches beyond one
None check.
"""

from __future__ import annotations

import logging
import threading
import time

from ..datastore.models import LeaderStoredReport
from ..datastore.store import Datastore

log = logging.getLogger(__name__)


def _ledger_book_admitted(tx, reports, results) -> None:
    """Book fresh admissions in the conservation ledger, INSIDE the
    same transaction as the puts (run_tx retries re-run the whole
    closure, so the count is exactly-once; replays book nothing). Then
    give the `ledger.drop_report` chaos failpoint its window: it
    deletes one just-admitted row AFTER the counter booked it — a
    silent loss the ledger's ingest equation must surface within one
    sampler interval."""
    from .. import failpoints, ledger

    per_task: dict = {}
    for r, fresh in zip(reports, results):
        if fresh:
            per_task[r.task_id] = per_task.get(r.task_id, 0) + 1
    for task_id, n in per_task.items():
        ledger.count_admitted(tx, task_id, n)
    if not per_task:
        return
    try:
        failpoints.hit("ledger.drop_report")
    except failpoints.FailpointError:
        for r, fresh in zip(reports, results):
            if fresh:
                tx.delete_client_report(r.task_id, r.report_id)
                log.error(
                    "failpoint ledger.drop_report: silently dropped admitted"
                    " report %s of task %s",
                    r.report_id,
                    r.task_id,
                )
                break


class _Pending:
    __slots__ = ("report", "event", "fresh", "error", "on_done")

    def __init__(self, report: LeaderStoredReport, on_done=None):
        self.report = report
        self.event = threading.Event()
        self.fresh: bool | None = None
        self.error: BaseException | None = None
        # optional callback, run on the flusher thread after the
        # outcome is recorded (the ingest pipeline resolves its upload
        # tickets here instead of parking a thread per report)
        self.on_done = on_done


class ReportWriteBatcher:
    """Blocking writes with group-commit flushes. Request threads call
    `write_report` and park until their batch's transaction commits."""

    def __init__(
        self,
        ds: Datastore,
        max_batch_size: int = 100,
        max_write_delay_ms: int = 0,
        journal=None,
        spill_latency_s: float = 0.0,
    ):
        self.ds = ds
        self.max_batch_size = max_batch_size
        self.max_write_delay_s = max_write_delay_ms / 1000.0
        # optional durable spill journal (ingest.journal.UploadJournal):
        # None = the pre-journal flush path, unchanged byte for byte
        self.journal = journal
        # commit latency past this spills subsequent flushes (0 = only
        # connection-class errors / supervisor-down spill)
        self.spill_latency_s = float(spill_latency_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._buffer: list[_Pending] = []
        self._flusher: threading.Thread | None = None
        self._stop = False

    def write_report(self, report: LeaderStoredReport, timeout_s: float = 30.0) -> bool:
        """Queue + wait for the group commit; returns False on replay."""
        pending = self.submit_report(report)
        if not pending.event.wait(timeout_s):
            raise TimeoutError("report write batch did not flush in time")
        if pending.error is not None:
            raise pending.error
        assert pending.fresh is not None
        return pending.fresh

    def submit_report(self, report: LeaderStoredReport, on_done=None) -> _Pending:
        """Queue without waiting. The returned _Pending's event fires —
        and `on_done(pending)` runs on the flusher thread — once its
        batch's transaction commits (pending.fresh) or fails
        (pending.error)."""
        pending = _Pending(report, on_done)
        with self._cv:
            if self._stop:
                raise RuntimeError("report writer is closed")
            self._buffer.append(pending)
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flush_loop, name="report-writer", daemon=True
                )
                self._flusher.start()
            self._cv.notify()
        return pending

    def flush_now(self) -> None:
        """Flush whatever is buffered synchronously (tests/shutdown)."""
        with self._cv:
            batch, self._buffer = self._buffer, []
        if batch:
            self._flush(batch)

    def close(self) -> None:
        """Stop the flusher thread after draining (shutdown path)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        flusher = self._flusher
        if flusher is not None:
            flusher.join(timeout=5)
        self.flush_now()

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while not self._buffer:
                    if self._stop:
                        return
                    self._cv.wait()
                if self.max_write_delay_s > 0:
                    # optional coalescing window (off by default): wait
                    # until the batch fills or the window closes
                    deadline = time.monotonic() + self.max_write_delay_s
                    while len(self._buffer) < self.max_batch_size and not self._stop:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                batch = self._buffer[: self.max_batch_size]
                self._buffer = self._buffer[self.max_batch_size :]
            if batch:  # a concurrent flush_now may have drained it
                self._flush(batch)

    def flush_direct(self, reports: list[LeaderStoredReport]) -> list[bool]:
        """One transaction for `reports`, NEVER spilling to the journal
        (the journal replayer's path — spilling a replay back into the
        journal would loop). Returns fresh-vs-replayed per report;
        raises on failure."""

        def tx_fn(tx):
            results = [tx.put_client_report(r) for r in reports]
            _ledger_book_admitted(tx, reports, results)
            return results

        return self.ds.run_tx(tx_fn, "upload_journal_replay")

    def _should_spill_without_trying(self) -> bool:
        """Skip the doomed datastore attempt entirely while the
        supervisor says the database is not up: during an outage every
        flush would otherwise burn run_tx's full retry budget before
        spilling, turning ~ms acks into ~second acks."""
        if self.journal is None:
            return False
        supervisor = getattr(self.ds, "supervisor", None)
        return supervisor is not None and supervisor.state != "up"

    def _spill(self, batch: list[_Pending]) -> None:
        """Journal the batch (fsync-on-ack) and resolve every waiter as
        fresh: durability now rests on the journal; replay dedups any
        true duplicate. Raises (JournalFull included) on failure."""
        self.journal.append_batch([p.report for p in batch])
        for p in batch:
            p.fresh = True

    def _flush(self, batch: list[_Pending]) -> None:
        """One transaction for the whole batch (reference :96-165)."""
        from .. import failpoints
        from ..trace import span

        try:
            # flush-failure injection: the whole batch's waiters must see
            # the error (fan-out below), and the upload handlers must map
            # it to a 500 problem document, never a silent 201
            failpoints.hit(
                "report_writer.flush",
                error_factory=lambda: RuntimeError(
                    "injected flush failure (failpoint report_writer.flush)"
                ),
            )

            if self._should_spill_without_trying():
                with span("upload.flush_spill", batch=len(batch)):
                    self._spill(batch)
                log.warning(
                    "datastore not up: spilled %d upload(s) to the journal",
                    len(batch),
                )
                return

            def tx_fn(tx):
                results = [tx.put_client_report(p.report) for p in batch]
                _ledger_book_admitted(tx, [p.report for p in batch], results)
                return results

            t0 = time.monotonic()
            try:
                with span("upload.flush_tx", batch=len(batch)):
                    results = self.ds.run_tx(tx_fn, "upload_batch")
            except BaseException as e:
                # connection-class failure + a journal: the ack contract
                # survives on local disk. Anything else (integrity,
                # injected flush faults, serialization exhaustion) still
                # fails loudly — those are not outages.
                if (
                    self.journal is not None
                    and getattr(self.ds, "classify_error", None) is not None
                    and self.ds.classify_error(e) == "connection"
                ):
                    with span("upload.flush_spill", batch=len(batch)):
                        self._spill(batch)
                    log.warning(
                        "datastore connection lost (%s); spilled %d upload(s)"
                        " to the journal",
                        e,
                        len(batch),
                    )
                    return
                raise
            elapsed = time.monotonic() - t0
            if self.journal is not None and 0 < self.spill_latency_s < elapsed:
                # the commit landed but took too long: tell the
                # supervisor so the NEXT flushes spill (bounded ack
                # latency through a brownout)
                supervisor = getattr(self.ds, "supervisor", None)
                if supervisor is not None:
                    supervisor.record_slow_commit(elapsed)
            for p, fresh in zip(batch, results):
                p.fresh = fresh
        except BaseException as e:  # fan the failure out to every waiter
            for p in batch:
                p.error = e
        finally:
            for p in batch:
                p.event.set()
                if p.on_done is not None:
                    try:
                        p.on_done(p)
                    except Exception:
                        # a bad callback must not take down the flusher
                        # or the rest of the batch's notifications
                        log.exception("report write on_done callback failed")
