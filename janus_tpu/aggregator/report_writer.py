"""Upload write batching.

Equivalent of reference aggregator/src/aggregator/report_writer.rs:24-165
(`ReportWriteBatcher`): buffer uploaded reports and flush them in a
single transaction when `max_batch_size` accumulate or
`max_write_delay` elapses, fanning the per-report outcome (fresh vs
replayed) back to each waiting upload request.
"""

from __future__ import annotations

import logging
import threading

from ..datastore.models import LeaderStoredReport
from ..datastore.store import Datastore

log = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("report", "event", "fresh", "error")

    def __init__(self, report: LeaderStoredReport):
        self.report = report
        self.event = threading.Event()
        self.fresh: bool | None = None
        self.error: BaseException | None = None


class ReportWriteBatcher:
    """Blocking writes with batched flushes. Request threads call
    `write_report` and park until their batch's transaction commits."""

    def __init__(
        self,
        ds: Datastore,
        max_batch_size: int = 100,
        max_write_delay_ms: int = 250,
    ):
        self.ds = ds
        self.max_batch_size = max_batch_size
        self.max_write_delay_s = max_write_delay_ms / 1000.0
        self._lock = threading.Lock()
        self._buffer: list[_Pending] = []
        self._timer: threading.Timer | None = None

    def write_report(self, report: LeaderStoredReport, timeout_s: float = 30.0) -> bool:
        """Queue + wait for the batch commit; returns False on replay."""
        pending = _Pending(report)
        with self._lock:
            self._buffer.append(pending)
            if len(self._buffer) >= self.max_batch_size:
                batch = self._take_locked()
            else:
                batch = None
                if self._timer is None:
                    self._timer = threading.Timer(self.max_write_delay_s, self._flush_timer)
                    self._timer.daemon = True
                    self._timer.start()
        if batch:
            self._flush(batch)
        if not pending.event.wait(timeout_s):
            raise TimeoutError("report write batch did not flush in time")
        if pending.error is not None:
            raise pending.error
        assert pending.fresh is not None
        return pending.fresh

    def flush_now(self) -> None:
        """Flush whatever is buffered (tests/shutdown)."""
        with self._lock:
            batch = self._take_locked()
        if batch:
            self._flush(batch)

    def _take_locked(self) -> list[_Pending]:
        batch, self._buffer = self._buffer, []
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return batch

    def _flush_timer(self) -> None:
        with self._lock:
            batch = self._take_locked()
        if batch:
            self._flush(batch)

    def _flush(self, batch: list[_Pending]) -> None:
        """One transaction for the whole batch (reference :96-165)."""
        try:
            def tx_fn(tx):
                return [tx.put_client_report(p.report) for p in batch]

            results = self.ds.run_tx(tx_fn, "upload_batch")
            for p, fresh in zip(batch, results):
                p.fresh = fresh
        except BaseException as e:  # fan the failure out to every waiter
            for p in batch:
                p.error = e
        finally:
            for p in batch:
                p.event.set()
