"""Aggregator: protocol handlers, job runners, HTTP shell.

Equivalent of reference aggregator/src/ (SURVEY.md section 2.5): the
per-request protocol brain (core.py), device-batch execution cache
(engine_cache.py), accumulator, job drivers (aggregation_job_driver,
collection_job_driver) over the generic lease JobDriver, the
aggregation-job creator, garbage collector, and the DAP HTTP layer
(http_handlers.py).

Execution model change vs the reference: everywhere the reference
iterates per report calling scalar field math, these handlers stage
columnar batches and invoke one jitted device computation
(SURVEY.md section 7 "Architecture stance").
"""

from .core import Aggregator, Config
from .errors import AggregatorError

__all__ = ["Aggregator", "Config", "AggregatorError"]
