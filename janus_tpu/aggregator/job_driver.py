"""Generic lease-based job driver loop.

Equivalent of reference aggregator/src/binary_utils/job_driver.rs:25-260:
acquire a batch of leases, step each job on a bounded worker pool,
rediscover with an adaptive delay, drain cleanly on shutdown.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor, wait
from dataclasses import dataclass

log = logging.getLogger(__name__)


@dataclass
class JobDriverConfig:
    """reference aggregator/src/config.rs:121-141."""

    job_discovery_interval_s: float = 0.2
    max_job_discovery_interval_s: float = 5.0
    max_concurrent_job_workers: int = 4
    worker_lease_duration_s: int = 600
    maximum_attempts_before_failure: int = 10
    # fractional jitter applied to every discovery sleep (delay *
    # uniform[1-j, 1+j]): a restarted fleet's replicas otherwise fall
    # into lockstep and thundering-herd the claim query every interval
    discovery_jitter: float = 0.25


def lease_deadline(clock, lease, skew_s: int) -> float:
    """time.monotonic() bound for one job step's work (device dispatch,
    helper HTTP, writes): lease remaining minus clock skew (reference
    job_driver.rs:191-196) — a stuck helper or a hung device must not
    outlive the lease and run the job concurrently with its
    re-acquirer.

    The skew must not swallow short (test/interop) leases: when the
    lease is shorter than twice the skew, keep half the remaining
    lease instead.

    An ALREADY-EXPIRED lease raises DeadlineExceeded instead of
    granting a floor budget (the old max(1.0, …) handed a dead lease a
    full second of doomed network time): the steppers translate it
    into an immediate step-back
    (janus_job_step_back_total{reason="deadline_expired"})."""
    remaining = lease.expiry.seconds - clock.now().seconds
    if remaining <= 0:
        from ..core.deadline import DeadlineExceeded

        raise DeadlineExceeded(
            f"lease already expired {-remaining}s ago; stepping back, not dialing"
        )
    bound = remaining - skew_s if remaining > 2 * skew_s else remaining / 2
    # the 1 s floor keeps short test/interop leases workable, but must
    # never extend PAST the lease: a near-expired lease's budget is
    # capped at exactly its remaining seconds, so the step can't run
    # concurrently with a re-acquirer
    return time.monotonic() + max(min(1.0, remaining), bound)


def deadline_request_timeout(
    deadline: float | None, attempt_cap_s: float | None = None
) -> float | None:
    """Per-attempt socket timeout capped to the remaining deadline.
    A deadline already in the past raises DeadlineExceeded — firing a
    doomed 0.1 s network attempt on a dead budget (the old floor) only
    burned helper admission and masked the step-back signal.

    `attempt_cap_s` is the overall-deadline/per-attempt split
    (docs/ARCHITECTURE.md "Surviving the other aggregator"): without a
    cap, one blackholed attempt legally consumes the ENTIRE remaining
    lease before the retry loop ever sees a second attempt — the cap
    bounds each attempt so the loop gets multiple swings (and the
    breaker multiple observations) inside one lease. The HttpClient's
    own `timeout` applies the same cap when built from the
    `helper_http:` stanza; this parameter makes the split explicit for
    callers with a bare client."""
    cap = None
    if deadline is not None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            from ..core.deadline import DeadlineExceeded

            raise DeadlineExceeded("request budget exhausted before the attempt")
        cap = remaining
    if attempt_cap_s is not None:
        cap = attempt_cap_s if cap is None else min(cap, attempt_cap_s)
    return cap


def datastore_down(ds) -> bool:
    """True while the datastore supervisor reports a hard outage —
    both drivers' acquirers park instead of burning an acquire (and a
    lease attempt on every job the tx WOULD claim) into a dead
    database; the discovery loop retries on its backoff."""
    supervisor = getattr(ds, "supervisor", None)
    return supervisor is not None and supervisor.state == "down"


def record_acquire(kind: str, jobs, shard=None) -> None:
    """Feed the fleet claim metrics from one acquire pass: claim-tx
    count by outcome, jobs leased, and — with a shard predicate — how
    many of them were STOLEN from another replica's shard (the
    steal-after-delay fallback draining a dead peer). A claim whose
    stored shard_key is negative was a clean HAND-BACK (shutdown
    drain released the affinity) — by design claimed cross-shard
    immediately, and never a steal: a routine rolling restart must not
    fire the starving-shard signal. Called by the drivers' acquirers
    AFTER run_tx returns, never inside the tx (a busy-retried attempt
    would double-count), and only when a claim tx actually ran."""
    from .. import metrics
    from ..datastore.store import job_shard_key

    labels = metrics.replica_labels()
    metrics.lease_acquire_tx_total.add(
        kind=kind, outcome="claimed" if jobs else "empty", **labels
    )
    if not jobs:
        return
    metrics.lease_acquired_jobs_total.add(len(jobs), kind=kind, **labels)
    if shard is not None and shard.active:

        def stored_key(a) -> int:
            sk = getattr(a, "shard_key", None)
            if sk is None:  # legacy-constructed acquired object
                sk = job_shard_key(a.task_id.data, _job_id_of(a).data)
            return sk

        # normalize the index like the claim SQL does, or an
        # out-of-range shard_index would misclassify every own-shard
        # claim as a steal
        index = shard.shard_index % shard.shard_count
        stolen = sum(
            1
            for a in jobs
            if (sk := stored_key(a)) >= 0 and sk % shard.shard_count != index
        )
        if stolen:
            metrics.lease_steals_total.add(stolen, kind=kind, **labels)


def _job_id_of(acquired):
    """The job-id field of either acquired-job shape."""
    if hasattr(acquired, "job_id"):
        return acquired.job_id
    return acquired.collection_job_id


def make_claim_acquirer(ds, kind: str, claim_fn, shard=None, peer_gate=None):
    """Shared acquirer body for both drivers: run `claim_fn(limit)`
    (the datastore claim run_tx) through the outage-tolerant wrapper
    and feed the fleet claim metrics ONLY when a claim transaction
    actually ran — a parked (supervisor-down) or connection-lost pass
    ran none, and counting it would fabricate claim traffic during
    exactly the outages the counters should stay honest through.
    `shard` feeds the steal classification (record_acquire).

    `peer_gate` is the PEER-outage analog of the supervisor park
    (aggregator/peer_health.py): a callable returning True while every
    known helper peer's circuit is open. A parked pass returns []
    without running the claim tx — a helper down for minutes must not
    have every replica claim-churning jobs it cannot step (steal-fence
    noise + wasted claim transactions across the whole fleet)."""

    def acquire(limit: int):
        if peer_gate is not None and peer_gate():
            return []
        ran = False

        def claim_tx():
            nonlocal ran
            out = claim_fn(limit)
            ran = True
            return out

        jobs = acquire_tolerating_outage(ds, claim_tx)
        if ran:
            record_acquire(kind, jobs, shard)
        return jobs

    return acquire


def acquire_tolerating_outage(ds, acquire_tx):
    """Shared acquirer body for both drivers: park (return []) while
    the supervisor reports down, absorb a CONNECTION-class acquire
    failure as 'no jobs this pass' (a datastore outage must not kill
    the driver process — the discovery loop IS the recovery
    mechanism), and re-raise everything else: a fatal error (broken
    schema) retried forever behind a healthy /readyz would be a silent
    stall, whereas a crash loop is visible to the orchestrator."""
    if datastore_down(ds):
        return []
    try:
        return acquire_tx()
    except Exception as e:
        if is_datastore_connection_error(ds, e):
            log.warning(
                "job acquisition failed (datastore connection lost); "
                "backing off before rediscovery"
            )
            return []
        raise


def datastore_reconnect_delay_s(ds, default: float = 5.0) -> float:
    """Step-back delay for a datastore-down step: the supervisor's
    reconnect cooldown when supervised, `default` otherwise."""
    supervisor = getattr(ds, "supervisor", None)
    return supervisor.reconnect_delay_s() if supervisor is not None else default


def is_datastore_connection_error(ds, e: BaseException) -> bool:
    """Classify an exception as a datastore connection loss (shared by
    both drivers' steppers; tolerant of test doubles without a
    classifier)."""
    classify = getattr(ds, "classify_error", None)
    return classify is not None and classify(e) == "connection"


class Stopper:
    """Cooperative shutdown flag (reference uses trillium Stopper)."""

    def __init__(self):
        self._event = threading.Event()

    def stop(self) -> None:
        self._event.set()

    @property
    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float) -> None:
        self._event.wait(timeout)


class JobDriver:
    """reference job_driver.rs:103 (run loop).

    acquirer(limit) -> list of acquired jobs;
    stepper(acquired) -> None (owns release/cancel).
    """

    def __init__(
        self,
        cfg: JobDriverConfig,
        acquirer,
        stepper,
        stopper: Stopper | None = None,
        releaser=None,
        pipeline=None,
    ):
        self.cfg = cfg
        self.acquirer = acquirer
        self.stepper = stepper
        self.stopper = stopper or Stopper()
        # optional releaser(acquired): called when a step fails during
        # shutdown drain so the lease is handed back immediately instead
        # of aging out a full TTL on the surviving peer (the drivers
        # pass their step_back, which preserves the attempt ledger)
        self.releaser = releaser
        # optional stage pipeline (aggregator/step_pipeline.py): when
        # set, leased jobs are submitted to pipeline.submit(acquired)
        # instead of running the serial stepper on a worker thread. The
        # returned futures resolve when the job's step fully completed
        # (the pipeline owns error mapping and drain-release), so the
        # discovery loop's worker accounting is unchanged.
        self.pipeline = pipeline

    def _submit(self, pool, acquired):
        if self.pipeline is not None:
            return self.pipeline.submit(acquired)
        return pool.submit(self._step_one, acquired)

    def run_once(self) -> int:
        """One acquire+step pass (barrier semantics — tests and one-shot
        tools); returns number of jobs stepped. The production loop is
        run(), which streams."""
        jobs = self.acquirer(self.cfg.max_concurrent_job_workers)
        if not jobs:
            return 0
        with ThreadPoolExecutor(max_workers=self.cfg.max_concurrent_job_workers) as pool:
            futures = [self._submit(pool, j) for j in jobs]
            wait(futures)
        return len(jobs)

    def _step_one(self, acquired) -> None:
        from ..trace import span

        try:
            with span("job.step", job=type(acquired).__name__):
                self.stepper(acquired)
        except Exception:
            if self.stopper.stopped and self.releaser is not None:
                # shutdown drain: this process will not retry — release
                # the lease now so a surviving peer picks the job up
                # immediately instead of after the lease TTL
                log.exception("job step failed during shutdown; releasing lease")
                try:
                    self.releaser(acquired)
                except Exception:
                    log.exception("shutdown lease release failed")
            else:
                log.exception("job step failed (lease will expire and retry)")

    def run(self) -> None:
        """Streaming discovery loop until stopped: acquire as worker
        permits free instead of barriering on whole batches, so one
        slow/hung job never idles the rest of the pool (reference
        job_driver.rs:119-186 acquires under a semaphore the same way).
        """
        import random
        from concurrent.futures import FIRST_COMPLETED

        delay = self.cfg.job_discovery_interval_s
        jitter = min(0.9, max(0.0, float(self.cfg.discovery_jitter)))
        in_flight: set = set()
        with ThreadPoolExecutor(max_workers=self.cfg.max_concurrent_job_workers) as pool:
            while not self.stopper.stopped:
                in_flight = {f for f in in_flight if not f.done()}
                free = self.cfg.max_concurrent_job_workers - len(in_flight)
                n = 0
                if free > 0:
                    # outage tolerance lives in the drivers' acquirers
                    # (acquire_tolerating_outage) so connection losses
                    # park the loop while fatal errors still crash
                    # loudly instead of stalling behind a ready /readyz
                    jobs = self.acquirer(free)
                    n = len(jobs)
                    for j in jobs:
                        in_flight.add(self._submit(pool, j))
                if n > 0:
                    delay = self.cfg.job_discovery_interval_s
                else:
                    delay = min(delay * 2, self.cfg.max_job_discovery_interval_s)
                # jittered sleep: N replicas restarted together must not
                # re-land on the claim query in lockstep every interval
                sleep = delay * random.uniform(1.0 - jitter, 1.0 + jitter)
                if in_flight:
                    # wake as soon as any permit frees (or re-discover)
                    wait(in_flight, timeout=sleep, return_when=FIRST_COMPLETED)
                else:
                    self.stopper.wait(sleep)
            # shutdown: drain in-flight steps (job_driver.rs:124-142)
            if in_flight:
                wait(in_flight)
