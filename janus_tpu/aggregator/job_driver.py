"""Generic lease-based job driver loop.

Equivalent of reference aggregator/src/binary_utils/job_driver.rs:25-260:
acquire a batch of leases, step each job on a bounded worker pool,
rediscover with an adaptive delay, drain cleanly on shutdown.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor, wait
from dataclasses import dataclass

log = logging.getLogger(__name__)


@dataclass
class JobDriverConfig:
    """reference aggregator/src/config.rs:121-141."""

    job_discovery_interval_s: float = 0.2
    max_job_discovery_interval_s: float = 5.0
    max_concurrent_job_workers: int = 4
    worker_lease_duration_s: int = 600
    maximum_attempts_before_failure: int = 10


class Stopper:
    """Cooperative shutdown flag (reference uses trillium Stopper)."""

    def __init__(self):
        self._event = threading.Event()

    def stop(self) -> None:
        self._event.set()

    @property
    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float) -> None:
        self._event.wait(timeout)


class JobDriver:
    """reference job_driver.rs:103 (run loop).

    acquirer(limit) -> list of acquired jobs;
    stepper(acquired) -> None (owns release/cancel).
    """

    def __init__(self, cfg: JobDriverConfig, acquirer, stepper, stopper: Stopper | None = None):
        self.cfg = cfg
        self.acquirer = acquirer
        self.stepper = stepper
        self.stopper = stopper or Stopper()

    def run_once(self) -> int:
        """One acquire+step pass; returns number of jobs stepped."""
        jobs = self.acquirer(self.cfg.max_concurrent_job_workers)
        if not jobs:
            return 0
        with ThreadPoolExecutor(max_workers=self.cfg.max_concurrent_job_workers) as pool:
            futures = [pool.submit(self._step_one, j) for j in jobs]
            wait(futures)
        return len(jobs)

    def _step_one(self, acquired) -> None:
        try:
            self.stepper(acquired)
        except Exception:
            log.exception("job step failed (lease will expire and retry)")

    def run(self) -> None:
        """Adaptive-delay discovery loop until stopped (job_driver.rs:119-186)."""
        delay = self.cfg.job_discovery_interval_s
        while not self.stopper.stopped:
            n = self.run_once()
            if n > 0:
                delay = self.cfg.job_discovery_interval_s
            else:
                delay = min(delay * 2, self.cfg.max_job_discovery_interval_s)
            self.stopper.wait(delay)
