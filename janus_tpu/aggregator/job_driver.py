"""Generic lease-based job driver loop.

Equivalent of reference aggregator/src/binary_utils/job_driver.rs:25-260:
acquire a batch of leases, step each job on a bounded worker pool,
rediscover with an adaptive delay, drain cleanly on shutdown.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor, wait
from dataclasses import dataclass

log = logging.getLogger(__name__)


@dataclass
class JobDriverConfig:
    """reference aggregator/src/config.rs:121-141."""

    job_discovery_interval_s: float = 0.2
    max_job_discovery_interval_s: float = 5.0
    max_concurrent_job_workers: int = 4
    worker_lease_duration_s: int = 600
    maximum_attempts_before_failure: int = 10


def lease_deadline(clock, lease, skew_s: int) -> float:
    """time.monotonic() bound for one job step's network work: lease
    remaining minus clock skew (reference job_driver.rs:191-196) — a
    stuck helper must not outlive the lease and run the job
    concurrently with its re-acquirer.

    The skew must not swallow short (test/interop) leases: when the
    lease is shorter than twice the skew, keep half the remaining
    lease instead."""
    remaining = lease.expiry.seconds - clock.now().seconds
    bound = remaining - skew_s if remaining > 2 * skew_s else remaining / 2
    return time.monotonic() + max(1.0, bound)


def deadline_request_timeout(deadline: float | None) -> float | None:
    """Per-attempt socket timeout capped to the remaining deadline."""
    if deadline is None:
        return None
    return max(0.1, deadline - time.monotonic())


class Stopper:
    """Cooperative shutdown flag (reference uses trillium Stopper)."""

    def __init__(self):
        self._event = threading.Event()

    def stop(self) -> None:
        self._event.set()

    @property
    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float) -> None:
        self._event.wait(timeout)


class JobDriver:
    """reference job_driver.rs:103 (run loop).

    acquirer(limit) -> list of acquired jobs;
    stepper(acquired) -> None (owns release/cancel).
    """

    def __init__(
        self,
        cfg: JobDriverConfig,
        acquirer,
        stepper,
        stopper: Stopper | None = None,
        releaser=None,
    ):
        self.cfg = cfg
        self.acquirer = acquirer
        self.stepper = stepper
        self.stopper = stopper or Stopper()
        # optional releaser(acquired): called when a step fails during
        # shutdown drain so the lease is handed back immediately instead
        # of aging out a full TTL on the surviving peer (the drivers
        # pass their step_back, which preserves the attempt ledger)
        self.releaser = releaser

    def run_once(self) -> int:
        """One acquire+step pass (barrier semantics — tests and one-shot
        tools); returns number of jobs stepped. The production loop is
        run(), which streams."""
        jobs = self.acquirer(self.cfg.max_concurrent_job_workers)
        if not jobs:
            return 0
        with ThreadPoolExecutor(max_workers=self.cfg.max_concurrent_job_workers) as pool:
            futures = [pool.submit(self._step_one, j) for j in jobs]
            wait(futures)
        return len(jobs)

    def _step_one(self, acquired) -> None:
        from ..trace import span

        try:
            with span("job.step", job=type(acquired).__name__):
                self.stepper(acquired)
        except Exception:
            if self.stopper.stopped and self.releaser is not None:
                # shutdown drain: this process will not retry — release
                # the lease now so a surviving peer picks the job up
                # immediately instead of after the lease TTL
                log.exception("job step failed during shutdown; releasing lease")
                try:
                    self.releaser(acquired)
                except Exception:
                    log.exception("shutdown lease release failed")
            else:
                log.exception("job step failed (lease will expire and retry)")

    def run(self) -> None:
        """Streaming discovery loop until stopped: acquire as worker
        permits free instead of barriering on whole batches, so one
        slow/hung job never idles the rest of the pool (reference
        job_driver.rs:119-186 acquires under a semaphore the same way).
        """
        from concurrent.futures import FIRST_COMPLETED

        delay = self.cfg.job_discovery_interval_s
        in_flight: set = set()
        with ThreadPoolExecutor(max_workers=self.cfg.max_concurrent_job_workers) as pool:
            while not self.stopper.stopped:
                in_flight = {f for f in in_flight if not f.done()}
                free = self.cfg.max_concurrent_job_workers - len(in_flight)
                n = 0
                if free > 0:
                    jobs = self.acquirer(free)
                    n = len(jobs)
                    for j in jobs:
                        in_flight.add(pool.submit(self._step_one, j))
                if n > 0:
                    delay = self.cfg.job_discovery_interval_s
                else:
                    delay = min(delay * 2, self.cfg.max_job_discovery_interval_s)
                if in_flight:
                    # wake as soon as any permit frees (or re-discover)
                    wait(in_flight, timeout=delay, return_when=FIRST_COMPLETED)
                else:
                    self.stopper.wait(delay)
            # shutdown: drain in-flight steps (job_driver.rs:124-142)
            if in_flight:
                wait(in_flight)
