"""Aggregation job driver (leader stepper) — the hot path.

Equivalent of reference aggregator/src/aggregator/aggregation_job_driver.rs:
49-894: acquire leases, read job + report state, run leader prepare,
PUT the init request to the helper, process its response, accumulate,
write back, release. The reference's three per-report loops
(leader_initialized :329-402, transition evaluation :467-496,
leader_continued + accumulate :530-726) are each one batched device
call here.

For the 1-round Prio3 VDAFs the whole job completes in a single step:
init -> helper responds finish/reject per report -> leader verifies the
prep message (joint-rand seed equality, host-side lane compare) ->
masked accumulate. Crash anywhere before the final write leaves the
job in step 0 with reports in START; the re-acquired lease replays the
init idempotently (helper request-hash dedup).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..core.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
    OutboundCircuitBreakers,
    default_breakers,
    peer_label,
)
from ..core.deadline import (
    DEADLINE_EXCEEDED_STATUS,
    DeadlineExceeded,
    current_deadline,
    deadline_scope,
)
from ..core.retries import Backoff, RequestAborted, retry_http_request
from ..datastore.models import (
    AcquiredAggregationJob,
    AggregationJobState,
    ReportAggregationState,
)
from .. import ledger, metrics
from ..datastore.store import Datastore
from ..messages import (
    AggregationJobContinueReq,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    Duration,
    PartialBatchSelector,
    PreEncoded,
    PrepareError,
    PrepareInit,
    PrepareStepResult,
    ReportIdChecksum,
    ReportShare,
    ReportMetadata,
    decode_prepare_resps_fast,
    encode_report_share_raw,
)
from ..messages.codec import DecodeError
from ..task import Task
from ..vdaf.registry import circuit_for
from ..vdaf.wire import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    Prio3Wire,
    decode_field_rows,
    decode_pingpong,
    encode_field_rows,
    encode_pingpong,
    encode_pingpong_share_column,
    flat_scatter_indices,
    pingpong_finish_frame_matches,
    seeds_to_lanes,
)
from .accumulator import (
    Accumulator,
    accumulate_batched,
    bucket_metadata,
    fixed_size_batch_id,
    group_batch_buckets,
)
from .engine_cache import DeviceHangError, EngineCache, engine_cache

log = logging.getLogger(__name__)


def _err_or_default(err) -> PrepareError:
    """PrepareError.BATCH_COLLECTED has enum value 0 (falsy), so the
    `err or DEFAULT` idiom silently rewrites it; compare against None."""
    return err if err is not None else PrepareError.VDAF_PREP_ERROR


# watchdog bound for resident-state fetches issued from threads with no
# ambient lease deadline (background flusher, drain): long enough for a
# busy device to answer, short enough that a wedged one can't park the
# flush pass holding the engine's resident lock
RESIDENT_FLUSH_FETCH_BOUND_S = 30.0


@dataclass
class ResidentConfig:
    """Device-resident accumulator knobs (YAML `resident_accumulators:`
    stanza of the driver binary; docs/ARCHITECTURE.md "Resident
    aggregate state"). Disabled by default: resident mode trades the
    per-job share fetch + write for a bounded durability window (a HARD
    crash — not drain/eviction/quarantine, which all flush — loses the
    unflushed window; see ROBUSTNESS.md fault matrix)."""

    enabled: bool = False
    # flush-to-datastore cadence for dirty resident buffers (also the
    # background flusher's pass interval); the loss window of a hard
    # crash is bounded by roughly this much accumulation
    flush_interval_s: float = 5.0

    @classmethod
    def from_dict(cls, d: dict | None) -> "ResidentConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", False)),
            flush_interval_s=float(d.get("flush_interval_secs", 5.0)),
        )


@dataclass
class AggregationJobDriverConfig:
    batch_aggregation_shard_count: int = 1
    maximum_attempts_before_failure: int = 10
    http_backoff: Backoff = Backoff()
    # helper HTTP work is bounded by lease remaining minus this skew
    # (reference job_driver.rs:191-196) so a hung helper can't outlive
    # the lease and run the job concurrently with a re-acquirer
    worker_lease_clock_skew_s: int = 60
    # leader->helper outbound circuit breaker (core/circuit_breaker.py;
    # YAML outbound_circuit_breaker: section)
    circuit_breaker: CircuitBreakerConfig | None = None
    # floor for the breaker-open step-back reacquire delay so a job
    # whose cooldown is nearly over doesn't spin acquire/step-back
    min_step_back_delay_s: int = 1
    # device-resident accumulator state (ISSUE 12)
    resident: ResidentConfig = field(default_factory=ResidentConfig)


@dataclass
class InitStepState:
    """Carrier of one prio3 init step through the stage chain. The
    serial stepper and the step_pipeline schedule the SAME stage
    methods over this state, so the two execution modes cannot drift:
    stage_init fills the staging columns, device_init the device
    outputs, http_init the accept/continue columns, and the commit
    stages consume them."""

    acquired: AcquiredAggregationJob
    task: Task
    job: object
    pending: list
    reports: dict
    wire: Prio3Wire
    engine: object
    multi_round: bool
    # columnar staging (host prefetch stage)
    meas: object = None
    proof: object = None
    nonce_lanes: object = None
    blind_lanes: object = None
    public_parts: object = None
    ok: object = None
    failed: list = field(default_factory=list)
    # device init outputs (device lane)
    out0: object = None
    seed0: object = None
    ver0: object = None
    part0: object = None
    # HTTP leg outputs
    accept: object = None
    continue_msgs: list | None = None
    # accumulate output (device lane)
    accumulator: Accumulator | None = None
    # double-buffered staging handle (engine.prestage_leader, issued by
    # the pipeline's read stage while the lane runs the previous job)
    prestaged: object = None
    # resident-accumulate handles (device PendingDeltas + the per-bucket
    # merge entries), consumed post-commit by commit_finish
    resident_delta: object = None
    resident_entries: list | None = None
    resident_rids: list | None = None
    # block-sparse tasks (ISSUE 17): per-lane PUBLIC block indices from
    # the decoded public shares ([n, max_blocks] int32, -1 padding /
    # failed lanes) — the accumulate stages expand them to flat scatter
    # targets. NOT cleared by the pipeline's device-init stage: the
    # accumulate leg runs after HTTP, long after staging columns drop.
    block_idx: object = None


class AggregationJobDriver:
    """reference aggregation_job_driver.rs:49."""

    def __init__(
        self,
        ds: Datastore,
        http,
        cfg: AggregationJobDriverConfig | None = None,
        breakers: OutboundCircuitBreakers | None = None,
        stopper=None,
        peer_health=None,
    ):
        self.ds = ds
        self.http = http
        self.cfg = cfg or AggregationJobDriverConfig()
        # per-peer circuit breaker shared process-wide by default (the
        # collection driver sees the same helper health)
        self.breakers = (
            breakers if breakers is not None else default_breakers(self.cfg.circuit_breaker)
        )
        # peer-outage parking tracker (peer_health.PeerHealthTracker);
        # None = no parking, per-step breaker step-backs only
        self.peer_health = peer_health
        # shutdown Stopper: in-flight helper retries abort on SIGTERM so
        # the step can step back instead of spending the whole lease
        self.stopper = stopper
        # resident-flush cadence state (ISSUE 12): the last time this
        # driver pushed dirty resident buffers through the write-tx path
        self._resident_flush_lock = threading.Lock()
        # seeded to "now" so the first inline flush waits a full
        # interval (0.0 would flush on the very first commit: monotonic
        # time is process uptime, always past the interval)
        self._resident_last_flush = time.monotonic()

    # --- JobDriver callbacks (reference :840-894) ---
    def acquirer(self, lease_duration_s: int = 600, fleet=None):
        """Batched claim acquirer. `fleet` (config.FleetConfig) adds
        the shard predicate + steal-after fallback and stamps this
        replica's provenance tag into every minted lease token
        (docs/ARCHITECTURE.md "Running a fleet")."""
        from .job_driver import make_claim_acquirer

        shard = fleet.shard_spec() if fleet is not None else None
        holder = fleet.holder_tag() if fleet is not None else None
        return make_claim_acquirer(
            self.ds,
            "aggregation",
            lambda limit: self.ds.run_tx(
                lambda tx: tx.acquire_incomplete_aggregation_jobs(
                    Duration(lease_duration_s), limit, shard=shard, holder=holder
                ),
                "acquire_agg_jobs",
            ),
            shard=shard,
            peer_gate=self.peer_health.park_gate()
            if self.peer_health is not None
            else None,
        )

    def _lease_deadline(self, acquired) -> float:
        from .job_driver import lease_deadline

        return lease_deadline(
            self.ds.clock, acquired.lease, self.cfg.worker_lease_clock_skew_s
        )

    def stepper(self, acquired: AcquiredAggregationJob) -> None:
        if acquired.lease.attempts > self.cfg.maximum_attempts_before_failure:
            self.abandon_job(acquired)
            return
        try:
            self.step_aggregation_job(acquired)
        except Exception as e:
            if self.handle_step_error(acquired, e):
                return
            log.exception(
                "aggregation job %s step failed (attempt %d)",
                acquired.job_id,
                acquired.lease.attempts,
            )
            raise

    def handle_step_error(self, acquired: AcquiredAggregationJob, e: Exception) -> bool:
        """Map a step failure to the step-back / attempt-ledger
        semantics. Returns True when the failure was translated into a
        step-back (lease released early, attempt refunded) — the step
        is NOT the job's fault and must not march it toward
        abandonment. Shared by the serial stepper and every
        step_pipeline stage, so a failure maps identically no matter
        which stage thread it surfaced on."""
        if isinstance(e, CircuitOpenError):
            # the helper's circuit is open: release the lease with the
            # cooldown as backoff instead of failing the step
            self.step_back(
                acquired,
                "circuit_open",
                max(e.retry_in_s, self.cfg.min_step_back_delay_s),
            )
            return True
        if isinstance(e, RequestAborted):
            # shutdown drain: hand the lease back immediately
            self.step_back(acquired, "shutdown_drain", 0.0)
            return True
        if isinstance(e, DeadlineExceeded):
            # the lease budget died (expired lease, retry loop past the
            # bound, or the helper answered the conclusive 408): dead
            # work is dropped here and redone under a fresh lease —
            # never amplified by burning the attempt ledger
            self.step_back(acquired, "deadline_expired", 0.0)
            return True
        if isinstance(e, DeviceHangError):
            # the device dispatch hung and was abandoned; the engine is
            # quarantined (host fallback serves the retry) — not this
            # job's fault, step back with a short reacquire delay
            self.step_back(acquired, "device_hang", self.cfg.min_step_back_delay_s)
            return True
        from .job_driver import datastore_reconnect_delay_s, is_datastore_connection_error

        if is_datastore_connection_error(self.ds, e):
            # datastore outage mid-step: step back with the reconnect
            # cooldown (best effort; if the step-back tx also fails,
            # the lease ages out)
            self.step_back(
                acquired, "datastore_down", datastore_reconnect_delay_s(self.ds)
            )
            return True
        return False

    def step_back(
        self, acquired: AcquiredAggregationJob, reason: str, delay_s: float
    ) -> None:
        """Release the lease early (reacquirable after delay_s, attempt
        refunded) — a breaker-open helper or a draining process must
        neither burn lease TTLs nor march the job toward abandonment."""
        from ..datastore.store import TxConflict

        delay = max(0, int(delay_s))
        log.warning(
            "stepping back aggregation job %s (%s): lease released, reacquirable in %ds",
            acquired.job_id, reason, delay,
        )
        metrics.job_step_back_total.add(reason=reason, **metrics.replica_labels())
        # a shutdown drain is a clean hand-back to the REST of the
        # fleet: backdate the eligible-since so any surviving replica
        # claims it immediately, never waiting out the steal fence
        handback = reason == "shutdown_drain"
        try:
            self.ds.run_tx(
                lambda tx: tx.step_back_aggregation_job(
                    acquired,
                    reacquire_delay_s=delay,
                    count_attempt=False,
                    handback=handback,
                ),
                "step_back_agg_job",
            )
        except TxConflict:
            # lease already lost (expired / re-acquired): nothing to return
            log.info("step-back of %s found the lease already gone", acquired.job_id)
        except Exception:
            # datastore unreachable: the lease ages out on its own TTL —
            # the step-back is an optimization, never a correctness need
            log.warning(
                "step-back of %s could not reach the datastore; lease will age out",
                acquired.job_id,
            )

    def _stage_pending(self, task, wire, engine, pending, reports):
        """Columnar staging of stored leader shares -> device-ready
        arrays + per-report failure marks."""
        n = len(pending)
        meas_rows: list[bytes | None] = [None] * n
        proof_rows: list[bytes | None] = [None] * n
        blind_rows: list[bytes | None] = [None] * n
        part_rows0: list[bytes | None] = [None] * n
        part_rows1: list[bytes | None] = [None] * n
        failed = [None] * n  # PrepareError or None
        circ = wire.circ
        idx_rows: list | None = [None] * n if wire.sparse else None
        mlen = circ.input_len * wire.enc_size
        plen = circ.proof_len * wire.enc_size
        for i, ra in enumerate(pending):
            rep = reports.get(ra.report_id.data)
            if rep is None:
                failed[i] = PrepareError.REPORT_DROPPED
                continue
            payload = rep.leader_input_share
            if len(payload) != wire.leader_share_len:
                failed[i] = PrepareError.INVALID_MESSAGE
                continue
            meas_rows[i] = payload[:mlen]
            proof_rows[i] = payload[mlen : mlen + plen]
            if wire.uses_jr:
                blind_rows[i] = payload[mlen + plen :]
                try:
                    parts = wire.decode_public_share(rep.public_share)
                    part_rows0[i], part_rows1[i] = parts
                    if idx_rows is not None:
                        # validated PUBLIC block indices (the sparse
                        # decode rejects out-of-range / unsorted rows)
                        idx_rows[i] = parts.indices
                except DecodeError:
                    failed[i] = PrepareError.INVALID_MESSAGE

        # test-only fake failure injection on the leader init path
        # (the reference's dummy_vdaf prep_init_fn hook)
        if task.vdaf.fails_at("init"):
            for i in range(n):
                if failed[i] is None:
                    failed[i] = PrepareError.VDAF_PREP_ERROR

        jf = engine.p3.jf
        meas, ok_m = decode_field_rows(jf, meas_rows, circ.input_len)
        proof, ok_p = decode_field_rows(jf, proof_rows, circ.proof_len)
        nonce_lanes, _ = seeds_to_lanes([ra.report_id.data for ra in pending])
        ok = ok_m & ok_p & np.array([f is None for f in failed])
        if wire.uses_jr:
            blind_lanes, ok_b = seeds_to_lanes(blind_rows)
            p0, ok_p0 = seeds_to_lanes(part_rows0)
            p1, ok_p1 = seeds_to_lanes(part_rows1)
            ok = ok & ok_b & ok_p0 & ok_p1
            public_parts = np.stack([p0, p1], axis=1)
        else:
            blind_lanes = None
            public_parts = None
        if idx_rows is not None:
            block_idx = np.full((n, circ.max_blocks), -1, dtype=np.int32)
            for i, row in enumerate(idx_rows):
                if row is not None:
                    block_idx[i] = row
        else:
            block_idx = None
        return meas, proof, nonce_lanes, blind_lanes, public_parts, ok, failed, block_idx

    # --- the step (reference :102-726), decomposed into the stage
    # methods the step_pipeline schedules across its executors. The
    # serial path below composes exactly the same stages in order, so
    # the pipelined and classic steppers cannot drift apart. ---
    def read_job(self, acquired: AcquiredAggregationJob):
        """tx1: read everything (reference :144-233). Runs on the
        pipeline's prefetch stage — job k+1's read overlaps job k's
        device/HTTP phases."""

        def read(tx):
            task = tx.get_task(acquired.task_id)
            job = tx.get_aggregation_job(acquired.task_id, acquired.job_id)
            ras = tx.get_report_aggregations_for_job(acquired.task_id, acquired.job_id)
            reports = {}
            for ra in ras:
                if ra.state == ReportAggregationState.START:
                    reports[ra.report_id.data] = tx.get_client_report(
                        acquired.task_id, ra.report_id
                    )
            return task, job, ras, reports

        from ..trace import span

        with span("driver.read_tx"):
            return self.ds.run_tx(read, "step_agg_job_read")

    def release_job(self, acquired: AcquiredAggregationJob) -> None:
        self.ds.run_tx(lambda tx: tx.release_aggregation_job(acquired), "release")

    def step_aggregation_job(self, acquired: AcquiredAggregationJob) -> None:
        task, job, ras, reports = self.read_job(acquired)
        if job is None or task is None:
            raise RuntimeError("job or task vanished while leased")
        if job.state != AggregationJobState.IN_PROGRESS:
            self.release_job(acquired)
            return

        from ..trace import use_traceparent

        # adopt the trace the job's CREATOR persisted in the row: every
        # span below (stage/encode/http/engine/write — and the helper's
        # handler spans, via the propagated traceparent header) joins
        # that trace, no matter which driver process steps the job or
        # how many restarts separate the steps. The lease budget rides
        # the same scope (core/deadline.py): the engine watchdog bounds
        # device dispatches with it and the HTTP client stamps the
        # remainder on outbound helper requests (DAP-Janus-Deadline).
        with use_traceparent(job.trace_context), deadline_scope(
            self._lease_deadline(acquired)
        ):
            self._step_leased_job(acquired, task, job, ras, reports)

    def plan_step(self, acquired, task, job, ras):
        """Classify the leased step -> (kind, payload): 'continue'
        (WaitingLeader rows), 'poplar1', 'empty', or 'init' (the
        pipelined prio3 hot path) with the rows the stage works on."""
        waiting = [
            ra for ra in ras if ra.state == ReportAggregationState.WAITING_LEADER
        ]
        if waiting:
            return "continue", waiting
        pending = [ra for ra in ras if ra.state == ReportAggregationState.START]
        if task.vdaf.kind == "poplar1":
            return "poplar1", pending
        if not pending:
            return "empty", pending
        return "init", pending

    def _step_leased_job(self, acquired, task, job, ras, reports) -> None:
        kind, rows = self.plan_step(acquired, task, job, ras)
        if kind == "continue":
            # multi-round jobs park accepted reports in WaitingLeader
            # after init; a later step sends the continue request
            # (reference :439-514 CONTINUE path)
            self._continue_step(acquired, task, job, rows)
            return
        if kind == "poplar1":
            self._step_poplar1_init(acquired, task, job, rows, reports)
            return
        if kind == "empty":
            self.finish_empty(acquired, job)
            return
        st = self.stage_init(acquired, task, job, rows, reports)
        self.device_init(st)
        self.http_init(st)
        if st.multi_round:
            self.commit_park(st)
        else:
            self.device_accumulate(st)
            self.commit_finish(st)

    def finish_empty(self, acquired, job) -> None:
        # nothing to do; mark job finished
        def finish(tx):
            tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(finish, "step_agg_job_finish_empty")

    def stage_init(self, acquired, task, job, pending, reports) -> "InitStepState":
        """Host stage: columnar staging of stored leader shares into
        device-ready arrays (prefetch stage under the pipeline)."""
        from ..trace import span

        wire = Prio3Wire(circuit_for(task.vdaf))
        engine = engine_cache(task.vdaf, task.vdaf_verify_key)
        n = len(pending)
        with span("driver.stage", batch=n):
            (
                meas,
                proof,
                nonce_lanes,
                blind_lanes,
                public_parts,
                ok,
                failed,
                block_idx,
            ) = self._stage_pending(task, wire, engine, pending, reports)
        return InitStepState(
            acquired=acquired,
            task=task,
            job=job,
            pending=pending,
            reports=reports,
            wire=wire,
            engine=engine,
            multi_round=task.vdaf.rounds > 1,
            meas=meas,
            proof=proof,
            nonce_lanes=nonce_lanes,
            blind_lanes=blind_lanes,
            public_parts=public_parts,
            ok=ok,
            failed=failed,
            block_idx=block_idx,
        )

    def device_init(self, st: "InitStepState") -> None:
        """Device stage: batched leader prepare-init (reference hot
        loop :329-402). Owned by the pipeline's device lane. A
        prestaged column set (double-buffered staging: the read stage
        issued the H2D async while the lane ran the previous job) is
        consumed here; leader_init falls back to the host columns when
        it can't use it."""
        prestaged, st.prestaged = st.prestaged, None
        st.out0, st.seed0, st.ver0, st.part0 = st.engine.leader_init(
            st.nonce_lanes, st.public_parts, st.meas, st.proof, st.blind_lanes,
            ok=st.ok, prestaged=prestaged,
        )

    def http_init(self, st: "InitStepState") -> None:
        """HTTP stage: columnar request framing, the helper round trip,
        columnar response decode + host-side verification (reference
        :404-424 build/send, :530-726 response processing)."""
        from ..trace import span

        acquired, task, job, pending, reports = (
            st.acquired, st.task, st.job, st.pending, st.reports,
        )
        wire = st.wire
        n = len(pending)
        failed = st.failed
        # one vectorized framing pass over the whole batch (ISSUE 9):
        # the prep-share column becomes framed ping-pong messages in a
        # single numpy pass, and each PrepareInit body is spliced from
        # pre-encoded rows instead of running the Encoder per report
        with span("driver.encode_init", batch=n):
            frames = encode_pingpong_share_column(
                st.engine.p3.jf, st.ver0, st.part0 if wire.uses_jr else None
            )
            prep_inits = []
            send_idx = []
            for i, ra in enumerate(pending):
                if failed[i] is not None or not st.ok[i]:
                    if failed[i] is None:
                        failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                rep = reports[ra.report_id.data]
                prep_inits.append(
                    PreEncoded(
                        encode_report_share_raw(
                            ra.report_id.data,
                            ra.client_time.seconds,
                            rep.public_share,
                            rep.helper_encrypted_input_share,
                        )
                        + frames.row(i)
                    )
                )
                send_idx.append(i)

        multi_round = st.multi_round
        accept = np.zeros(n, dtype=bool)
        continue_msgs: list[bytes | None] = [None] * n
        if prep_inits:
            req = AggregationJobInitializeReq(
                job.aggregation_parameter,
                PartialBatchSelector.from_bytes(job.partial_batch_identifier),
                tuple(prep_inits),
            )
            with span("driver.http_init", reports=len(prep_inits)):
                body = self._send_init_request_raw(
                    task, acquired.job_id, req, acquired=acquired
                )
            col = decode_prepare_resps_fast(body)
            mapping = self._match_resps(
                [pending[i].report_id.data for i in send_idx], col
            )
            # jr seed rows for the order-aligned verify below, one
            # vectorized conversion for the whole batch
            seed_rows = (
                np.ascontiguousarray(np.asarray(st.seed0, dtype="<u8")).view(np.uint8)
                if wire.uses_jr and not multi_round
                else None
            )
            # process response (reference :530-726), host-side lane checks
            for k, i in enumerate(send_idx):
                j = k if mapping is None else mapping[k]
                if j is None:
                    failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                kind = col.kinds[j]
                if kind == PrepareStepResult.REJECT:
                    failed[i] = _err_or_default(col.errors[j])
                    continue
                msg = col.messages[j]
                if multi_round:
                    # helper answered ping-pong CONTINUE; the leader's
                    # next message (sent on a later step) finishes with
                    # the combined prep message (fake: echo)
                    if msg is None:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    try:
                        tag, prep_msg, _share = decode_pingpong(msg)
                    except DecodeError:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    if tag != PP_CONTINUE:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    continue_msgs[i] = encode_pingpong(PP_FINISH, prep_msg or b"", None)
                    accept[i] = True
                    continue
                if wire.uses_jr:
                    # the helper's answer must be finish(our jr seed):
                    # a two-compare fast path over the raw frame (the
                    # column decoder guarantees msg is exactly one
                    # well-formed self-delimiting frame)
                    verdict = (
                        pingpong_finish_frame_matches(msg, seed_rows[i].tobytes())
                        if msg is not None
                        else None
                    )
                    if verdict is None:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    if verdict is False:
                        failed[i] = PrepareError.VDAF_PREP_ERROR
                        continue
                accept[i] = True

        # test-only fake failure at the leader continue/evaluate stage
        # (the reference's dummy_vdaf prep_step_fn hook)
        if task.vdaf.fails_at("step"):
            for i in range(n):
                if accept[i]:
                    accept[i] = False
                    failed[i] = PrepareError.VDAF_PREP_ERROR

        st.accept = accept
        st.continue_msgs = continue_msgs

    def _match_resps(self, sent_ids: list[bytes], col) -> list[int | None] | None:
        """Order-aligned prepare-resp matching: DAP requires the helper
        to answer in request order, so verify alignment cheaply (one
        bytes compare per report, C speed) and skip the O(n) dict build.
        Returns None when aligned (identity mapping); otherwise counts
        the contract violation and falls back to the id->index dict."""
        if len(col.report_ids) == len(sent_ids) and all(
            a == b for a, b in zip(col.report_ids, sent_ids)
        ):
            return None
        metrics.prep_resp_order_mismatch_total.add()
        by_id = {rid: j for j, rid in enumerate(col.report_ids)}
        return [by_id.get(rid) for rid in sent_ids]

    def commit_park(self, st: "InitStepState") -> None:
        """Commit stage, multi-round: park accepted reports as
        WaitingLeader(out_share || msg); job stays in progress — a later
        driver step sends the continue request (reference stores the
        transition the same way, models.rs:714 WaitingLeader)."""
        import dataclasses

        out0_rows = encode_field_rows(st.engine.p3.jf, st.out0)
        new_ras = []
        for i, ra in enumerate(st.pending):
            if st.accept[i]:
                msg = st.continue_msgs[i]
                blob = len(msg).to_bytes(4, "big") + msg + out0_rows[i]
                new_ras.append(
                    dataclasses.replace(
                        ra,
                        state=ReportAggregationState.WAITING_LEADER,
                        prep_blob=blob,
                    )
                )
            else:
                err = _err_or_default(st.failed[i])
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        acquired = st.acquired

        task_id = st.task.task_id

        def write_waiting(tx):
            for ra in new_ras:
                tx.update_report_aggregation(ra)
            # conservation ledger: FAILED rows reach their terminal here
            # (parked WAITING rows stay in-flight) — booked in the same
            # tx so a run_tx retry can't double-count
            ledger.count_ra_outcomes(
                tx, task_id, new_ras,
                aggregation_parameter=st.job.aggregation_parameter,
            )
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(write_waiting, "step_agg_job_park")

    def device_accumulate(self, st: "InitStepState") -> None:
        """Device stage: masked accumulate (reference
        Accumulator::update :605-627). Owned by the device lane.

        Resident mode (ISSUE 12): instead of one masked reduce + host
        fetch per batch bucket, compute ALL buckets' sums as one device
        PendingDeltas (one [n] int32 upload, zero fetch) and record
        share=None entries in the job's Accumulator — the share bytes
        stay in device memory and merge into the engine's resident
        buffers only after the write tx commits (commit_finish). The
        classic path remains the fallback whenever the engine can't
        serve it (host fallback/quarantine) or the delta dispatch fails
        for a non-hang reason."""
        from ..trace import span

        st.accumulator = Accumulator(st.task, self.cfg.batch_aggregation_shard_count)
        metadatas = [ReportMetadata(ra.report_id, ra.client_time) for ra in st.pending]
        pbs = PartialBatchSelector.from_bytes(st.job.partial_batch_identifier)
        bid_fixed = fixed_size_batch_id(pbs)
        with span("driver.accumulate", batch=len(st.pending)):
            if (
                self.cfg.resident.enabled
                and isinstance(st.engine, EngineCache)
                and st.engine.resident_ready()
                and self._device_accumulate_resident(st, metadatas, bid_fixed)
            ):
                return
            accumulate_batched(
                st.task,
                st.engine,
                st.accumulator,
                st.out0,
                st.accept,
                metadatas,
                batch_identifier=bid_fixed,
                flat_idx=self._flat_idx(st),
            )

    @staticmethod
    def _flat_idx(st: "InitStepState"):
        """[n, compact_len] int32 scatter targets for a sparse job's
        staged block indices; None on dense tasks."""
        if not st.wire.sparse or st.block_idx is None:
            return None
        return flat_scatter_indices(st.block_idx, st.wire.circ)

    def _device_accumulate_resident(self, st, metadatas, bid_fixed) -> bool:
        """Resident accumulate attempt. True = st.accumulator holds
        share=None entries and st.resident_delta carries the device
        sums; False = caller must run the classic path."""
        n = len(metadatas)
        buckets = group_batch_buckets(st.task, metadatas, st.accept, bid_fixed)
        if not buckets:
            return True  # nothing accepted; nothing to merge either
        keys = list(buckets)
        lane_bucket = np.full(n, -1, dtype=np.int32)
        for j, bid in enumerate(keys):
            lane_bucket[buckets[bid]] = j
        try:
            delta = st.engine.aggregate_pending(
                st.out0, lane_bucket, len(keys), flat_idx=self._flat_idx(st)
            )
        except (DeviceHangError, DeadlineExceeded):
            raise  # step-back semantics, identical to the classic path
        except Exception:
            log.warning(
                "resident accumulate failed for job %s; falling back to the "
                "classic per-bucket path",
                st.acquired.job_id,
                exc_info=True,
            )
            return False
        entries = []
        rids0 = []
        for j, bid in enumerate(keys):
            lanes = buckets[bid]
            checksum, interval = bucket_metadata(st.task, metadatas, lanes)
            st.accumulator.update(
                bid,
                None,  # the share bytes live on device until flush
                len(lanes),
                checksum,
                interval,
                [metadatas[i].report_id for i in lanes],
            )
            entries.append(
                (
                    (st.task.task_id.data, st.job.aggregation_parameter, bid),
                    j,
                    len(lanes),
                    interval,
                )
            )
            rids0.append(metadatas[lanes[0]].report_id.data)
        st.resident_delta = delta
        st.resident_entries = entries
        st.resident_rids = rids0
        return True

    def commit_finish(self, st: "InitStepState") -> None:
        """Commit stage: tx2 writes results + releases the lease
        (reference :698-724)."""
        from ..trace import span

        acquired, job = st.acquired, st.job
        new_ras = []
        for i, ra in enumerate(st.pending):
            if st.accept[i]:
                new_ras.append(ra.finished())
            else:
                err = _err_or_default(st.failed[i])
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        # committing attempt's unmergeable set, carried out of the tx for
        # the post-commit e2e observation (run_tx may retry the closure)
        cell: dict = {}
        accumulator = st.accumulator

        def write(tx):
            # flush first: reports whose batch was collected mid-flight
            # fail individually with BATCH_COLLECTED (reference
            # flush_to_datastore unmergeable set, accumulator.rs:133-215)
            unmerged = accumulator.flush_to_datastore(tx)
            cell["unmerged"] = unmerged
            for ra in new_ras:
                if ra.report_id.data in unmerged:
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                tx.update_report_aggregation(ra)
            # conservation ledger: every row is terminal in this tx —
            # FINISHED books aggregated, FINISHED-but-unmerged books
            # rejected:batch_collected, FAILED books rejected:<err>
            # (param-fanout jobs book their own lane: one report
            # finishes once PER parameter)
            ledger.count_ra_outcomes(
                tx, job.task_id, new_ras, unmerged,
                aggregation_parameter=job.aggregation_parameter,
            )
            tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
            tx.release_aggregation_job(acquired)

        with span("driver.write_tx", batch=len(st.pending)):
            self.ds.run_tx(write, "step_agg_job_write")
        # resident mode: the device deltas merge into the engine's
        # resident buffers ONLY now, after the commit landed — a failed
        # write tx (or a step-back anywhere earlier) just drops the
        # PendingDeltas object, so the re-step under a fresh lease can
        # never double-merge. A hard crash in this window loses the
        # delta (the documented resident durability window,
        # ROBUSTNESS.md fault matrix).
        if st.resident_delta is not None:
            self._resident_post_commit(st, cell.get("unmerged", set()))
        # e2e SLO observed only AFTER the write committed: a failed step
        # retried under a fresh lease must not leave phantom samples
        from .accumulator import observe_finished_report_e2e

        observe_finished_report_e2e(self.ds.clock, new_ras, cell.get("unmerged", ()))

    # --- resident aggregate state: merge + flush (ISSUE 12) -----------
    def _resident_post_commit(self, st, unmerged: set) -> None:
        """Merge the job's committed deltas into resident buffers; flush
        LRU-evicted slots immediately; honor the flush cadence."""
        engine = st.engine
        # a bucket whose batch was collected mid-flight had ALL its
        # reports refused by flush_to_datastore (BATCH_COLLECTED) — its
        # delta must not enter the resident share either
        entries = [
            e
            for e, rid0 in zip(st.resident_entries, st.resident_rids)
            if rid0 not in unmerged
        ]
        delta, st.resident_delta = st.resident_delta, None
        if entries:
            try:
                evicted = engine.resident_merge(entries, delta)
            except Exception as merge_exc:
                # the commit LANDED but the merge didn't: the
                # contributions must not vanish — fetch the delta rows
                # directly and push them through the flush path. A
                # mid-loop failure leaves a merged PREFIX safely on
                # device (ResidentMergeError.merged); flushing those
                # again would double-count them when their slot
                # flushes, so only the remainder goes out directly.
                merged_keys = getattr(merge_exc, "merged", frozenset())
                remaining = [e for e in entries if e[0] not in merged_keys]
                log.error(
                    "resident merge failed post-commit for job %s (%d of %d "
                    "buckets merged before the failure); flushing the "
                    "remaining delta rows directly",
                    st.acquired.job_id,
                    len(merged_keys),
                    len(entries),
                    exc_info=True,
                )
                recs = []
                try:
                    recs = engine.fetch_delta_records(remaining, delta)
                except Exception:
                    metrics.engine_resident_flushes_total.add(
                        len(remaining), reason="merge_failed", outcome="lost"
                    )
                    ledger.count_lost(self.ds, st.task.task_id, len(remaining))
                    log.exception(
                        "resident delta fetch also failed; %d bucket "
                        "contribution(s) of job %s are LOST",
                        len(remaining),
                        st.acquired.job_id,
                    )
                    recs = []
                if recs:
                    self.flush_resident_records(engine, recs, reason="merge_failed")
            else:
                if evicted:
                    self.flush_resident_records(engine, evicted, reason="eviction")
        self.maybe_flush_resident(engine)

    def maybe_flush_resident(self, engine) -> None:
        """Honor the flush cadence inline (the background flusher covers
        idle periods; this keeps a busy serial driver bounded too)."""
        interval = self.cfg.resident.flush_interval_s
        now = time.monotonic()
        with self._resident_flush_lock:
            if now - self._resident_last_flush < interval:
                return
            self._resident_last_flush = now
        self.flush_engine_resident(engine, reason="interval")

    def flush_engine_resident(self, engine, reason: str = "interval") -> int:
        """Take every resident slot of `engine` and write the shares
        through the existing batch-aggregation write-tx path. Returns
        the number of buffers flushed. A take failure (wedged device)
        leaves the slots resident — retried on the next pass/drain."""
        if not isinstance(engine, EngineCache):
            return 0
        from .job_driver import datastore_down

        if reason != "drain" and datastore_down(self.ds):
            # flushing into a known-down store would pop the slots and
            # then LOSE the fetched shares when the tx fails (the flush
            # is at-most-once by design — no idempotency key guards a
            # re-flush against double-merging on a commit-ack loss).
            # Leave the state resident; the flusher retries after the
            # supervisor reports the store back up.
            return 0
        try:
            if current_deadline() is None:
                # flusher/drain threads carry no ambient lease deadline,
                # and without one the dispatch watchdog degrades to a
                # direct call — a wedged device would then block this
                # fetch FOREVER while resident_take holds the engine's
                # resident lock, deadlocking every commit worker behind
                # it. Bound the fetch; a timeout restores the slots and
                # the next pass retries.
                with deadline_scope(
                    time.monotonic() + RESIDENT_FLUSH_FETCH_BOUND_S
                ):
                    recs = engine.resident_take()
            else:
                recs = engine.resident_take()
        except Exception:
            log.warning(
                "resident take failed for %s (%s); state stays resident for retry",
                engine.inst.kind,
                reason,
                exc_info=True,
            )
            return 0
        if not recs:
            return 0
        return self.flush_resident_records(engine, recs, reason)

    def flush_resident_state(self, reason: str = "interval") -> int:
        """Flush every live engine's resident buffers (drain hook; also
        the background flusher's pass body)."""
        from .engine_cache import live_engines

        # share the cadence stamp with the inline post-commit check:
        # without this, a busy driver with the background flusher
        # running pays the full take + flush tx TWICE per interval
        with self._resident_flush_lock:
            self._resident_last_flush = time.monotonic()
        flushed = 0
        for eng in live_engines():
            flushed += self.flush_engine_resident(
                eng,
                reason if eng.resident_ready() else "quarantine",
            )
        return flushed

    def flush_resident_records(self, engine, recs: list, reason: str) -> int:
        """Persist fetched resident shares through the existing
        Accumulator write-tx path (share-only merges: count 0, identity
        checksum — counts/checksums were durable at each job's commit).
        A batch collected before its flush arrived is a LOST share
        (counted + ERROR-logged); a deleted task is stale state."""
        from ..messages import TaskId

        by_task: dict[bytes, list] = {}
        for r in recs:
            by_task.setdefault(r["key"][0], []).append(r)
        flushed = 0
        for task_id_bytes, rows in by_task.items():
            outcome_cell: dict = {}

            def write(tx, task_id_bytes=task_id_bytes, rows=rows, cell=outcome_cell):
                cell.clear()
                task = tx.get_task(TaskId(task_id_bytes))
                if task is None:
                    cell["stale"] = len(rows)
                    return
                accs: dict[bytes, Accumulator] = {}
                lost = flushed_n = 0
                for r in rows:
                    _, agg_param, bid = r["key"]
                    if tx.batch_has_collected_shard(task.task_id, bid, agg_param):
                        lost += 1
                        log.error(
                            "resident share for task %s batch %r arrived AFTER "
                            "collection; the share is lost (flush reason=%s)",
                            task.task_id,
                            bid[:16],
                            reason,
                        )
                        continue
                    acc = accs.get(agg_param)
                    if acc is None:
                        acc = accs[agg_param] = Accumulator(
                            task,
                            self.cfg.batch_aggregation_shard_count,
                            aggregation_parameter=agg_param,
                            count_metrics=False,
                        )
                    acc.update(
                        bid,
                        acc.field.encode_vec(r["share"]),
                        0,
                        ReportIdChecksum(),
                        r["interval"],
                        [],
                    )
                    flushed_n += 1
                for acc in accs.values():
                    acc.flush_to_datastore(tx)
                if lost:
                    # first-class ledger terminal for share-mass loss,
                    # booked in the SAME tx that established the loss
                    tx.increment_task_counters(task.task_id, {"lost": lost})
                cell["lost"] = lost
                cell["flushed"] = flushed_n

            try:
                self.ds.run_tx(write, "flush_resident")
            except Exception:
                log.exception(
                    "resident flush tx failed (%d buffer(s), reason=%s); the "
                    "fetched shares are LOST",
                    len(rows),
                    reason,
                )
                metrics.engine_resident_flushes_total.add(
                    len(rows), reason=reason, outcome="lost"
                )
                ledger.count_lost(self.ds, TaskId(task_id_bytes), len(rows))
                continue
            for outcome in ("flushed", "lost", "stale"):
                n = outcome_cell.get(outcome, 0)
                if n:
                    metrics.engine_resident_flushes_total.add(
                        n, reason=reason, outcome=outcome
                    )
            flushed += outcome_cell.get("flushed", 0)
        return flushed

    def _step_poplar1_init(self, acquired, task: Task, job, pending, reports) -> None:
        """Poplar1 leader init (see aggregator.poplar1_ops docstring):
        evaluate IDPF shares at the job's aggregation parameter, send
        sketch shares, verify the helper's combined sketch, park
        WaitingLeader for the continue round."""
        import dataclasses

        from .poplar1_ops import Poplar1Ops

        pop = Poplar1Ops(task.vdaf.bits, task.vdaf_verify_key)
        param = pop.decode_param(job.aggregation_parameter)
        F = pop.field_for(param)

        if not pending:
            def finish_empty(tx):
                tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
                tx.release_aggregation_job(acquired)

            self.ds.run_tx(finish_empty, "step_p1_job_finish_empty")
            return

        n = len(pending)
        failed: list = [None] * n
        evals: dict[int, tuple] = {}  # i -> (prep state, y0, [A0, B0])
        items = []
        item_idx = []
        for i, ra in enumerate(pending):
            rep = reports.get(ra.report_id.data)
            if rep is None:
                failed[i] = PrepareError.REPORT_DROPPED
                continue
            items.append(
                (rep.public_share, rep.leader_input_share, ra.report_id.data)
            )
            item_idx.append(i)
        # one batched device IDPF walk + sketch for the whole job
        for i, res in zip(item_idx, pop.round1_batch(0, items, param)):
            if isinstance(res, ValueError):
                failed[i] = PrepareError.INVALID_MESSAGE
            else:
                evals[i] = res

        prep_inits = []
        send_idx = []
        for i, ra in enumerate(pending):
            if failed[i] is not None:
                continue
            rep = reports[ra.report_id.data]
            _, _, msg1_0 = evals[i]
            prep_inits.append(
                PrepareInit(
                    ReportShare(
                        ReportMetadata(ra.report_id, ra.client_time),
                        rep.public_share,
                        rep.helper_encrypted_input_share,
                    ),
                    encode_pingpong(PP_INITIALIZE, None, pop.encode_vec(param, msg1_0)),
                )
            )
            send_idx.append(i)

        parked: dict[int, bytes] = {}  # i -> WaitingLeader blob
        if prep_inits:
            req = AggregationJobInitializeReq(
                job.aggregation_parameter,
                PartialBatchSelector.from_bytes(job.partial_batch_identifier),
                tuple(prep_inits),
            )
            resp = self._send_init_request(task, acquired.job_id, req, acquired=acquired)
            by_id = {pr.report_id: pr for pr in resp.prepare_resps}
            for i in send_idx:
                ra = pending[i]
                pr = by_id.get(ra.report_id)
                if pr is None or pr.result.kind == PrepareStepResult.REJECT:
                    failed[i] = _err_or_default(
                        pr.result.prepare_error if pr is not None else None
                    )
                    continue
                try:
                    tag, prep_msg, helper_share = decode_pingpong(pr.result.message)
                    if tag != PP_CONTINUE or helper_share is None:
                        raise DecodeError("expected ping-pong continue")
                    es = pop.enc_size(param)
                    # helper share = enc(A1)||enc(B1)||enc(sigma1)
                    msg1_1 = pop.decode_fixed_vec(param, helper_share[: 2 * es], 2)
                    sigma1 = pop.decode_elem(param, helper_share[2 * es :])
                except (DecodeError, ValueError):
                    failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                st0, y0, msg1_0 = evals[i]
                sigma0, combined = pop.round2(st0, msg1_0, msg1_1)
                # the helper's claimed round-1 prep message must equal our
                # own combination, and the quadratic sketch must verify
                # (sigma0 + sigma1 == 0 <=> y one-hot or all-zero)
                if prep_msg != pop.encode_vec(param, combined) or F.add(
                    sigma0, sigma1
                ) != 0:
                    failed[i] = PrepareError.VDAF_PREP_ERROR
                    continue
                msg = encode_pingpong(PP_FINISH, pop.encode_elem(param, sigma0), None)
                parked[i] = (
                    len(msg).to_bytes(4, "big") + msg + pop.encode_vec(param, y0)
                )

        new_ras = []
        for i, ra in enumerate(pending):
            if i in parked:
                new_ras.append(
                    dataclasses.replace(
                        ra,
                        state=ReportAggregationState.WAITING_LEADER,
                        prep_blob=parked[i],
                    )
                )
            else:
                err = _err_or_default(failed[i])
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        def write_waiting(tx):
            for ra in new_ras:
                tx.update_report_aggregation(ra)
            ledger.count_ra_outcomes(
                tx, task.task_id, new_ras,
                aggregation_parameter=job.aggregation_parameter,
            )
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(write_waiting, "step_p1_job_park")

    def _continue_step(self, acquired, task: Task, job, waiting) -> None:
        """Send the ord-matched continue request for WaitingLeader rows
        and finish the job (reference :439-514 + :530-726)."""
        import dataclasses

        if task.vdaf.kind == "poplar1":
            from .poplar1_ops import Poplar1Ops

            pop = Poplar1Ops(task.vdaf.bits)
            field = pop.field_for(pop.decode_param(job.aggregation_parameter))
        else:
            field = circuit_for(task.vdaf).FIELD
        msgs = []
        outs = []
        for ra in waiting:
            mlen = int.from_bytes(ra.prep_blob[:4], "big")
            msgs.append(ra.prep_blob[4 : 4 + mlen])
            outs.append(ra.prep_blob[4 + mlen :])
        # the stored msgs are already-framed ping-pong messages; splice
        # them raw (PrepareContinue = report_id || message) instead of
        # re-validating each frame through the dataclass codec
        req = AggregationJobContinueReq(
            AggregationJobStep(job.step + 1),
            tuple(
                PreEncoded(ra.report_id.data + msg)
                for ra, msg in zip(waiting, msgs)
            ),
        )
        from ..trace import span

        with span("driver.http_continue", reports=len(waiting)):
            body = self._send_agg_job_request_raw(
                task, acquired.job_id, "POST", req, acquired=acquired
            )
        col = decode_prepare_resps_fast(body)
        mapping = self._match_resps([ra.report_id.data for ra in waiting], col)

        accumulator = Accumulator(
            task,
            self.cfg.batch_aggregation_shard_count,
            field=field,
            aggregation_parameter=job.aggregation_parameter,
        )
        pbs = PartialBatchSelector.from_bytes(job.partial_batch_identifier)
        fixed_bid = fixed_size_batch_id(pbs)
        new_ras = []
        for k, (ra, out_enc) in enumerate(zip(waiting, outs)):
            j = k if mapping is None else mapping[k]
            if j is not None and col.kinds[j] == PrepareStepResult.FINISHED:
                from ..messages import Interval

                bid = fixed_bid or Interval(
                    ra.client_time.to_batch_interval_start(task.time_precision),
                    task.time_precision,
                ).to_bytes()
                accumulator.update_single(
                    bid, field.decode_vec(out_enc), ra.report_id, ra.client_time
                )
                new_ras.append(
                    dataclasses.replace(
                        ra, state=ReportAggregationState.FINISHED, prep_blob=b""
                    )
                )
            else:
                err = _err_or_default(
                    col.errors[j]
                    if j is not None and col.kinds[j] == PrepareStepResult.REJECT
                    else None
                )
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        new_job = dataclasses.replace(
            job, state=AggregationJobState.FINISHED, step=job.step + 1
        )
        cell: dict = {}

        def write(tx):
            unmerged = accumulator.flush_to_datastore(tx)
            cell["unmerged"] = unmerged
            for ra in new_ras:
                if ra.report_id.data in unmerged:
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                tx.update_report_aggregation(ra)
            ledger.count_ra_outcomes(
                tx, task.task_id, new_ras, unmerged,
                aggregation_parameter=job.aggregation_parameter,
            )
            tx.update_aggregation_job(new_job)
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(write, "step_agg_job_continue_write")
        # e2e SLO observed only post-commit (see the init path above)
        from .accumulator import observe_finished_report_e2e

        observe_finished_report_e2e(self.ds.clock, new_ras, cell.get("unmerged", ()))

    def _send_agg_job_request(
        self,
        task: Task,
        job_id,
        method: str,
        req,
        extra_headers: dict | None = None,
        deadline: float | None = None,
        acquired=None,
    ) -> AggregationJobResp:
        return AggregationJobResp.from_bytes(
            self._send_agg_job_request_raw(
                task, job_id, method, req,
                extra_headers=extra_headers, deadline=deadline, acquired=acquired,
            )
        )

    def _send_agg_job_request_raw(
        self,
        task: Task,
        job_id,
        method: str,
        req,
        extra_headers: dict | None = None,
        deadline: float | None = None,
        acquired=None,
    ) -> bytes:
        """Shared PUT(init)/POST(continue) to the helper's
        aggregation_jobs endpoint: URL, auth, deadline-capped timeouts,
        retries; returns the raw response body (the callers' columnar
        decoders parse it)."""
        import base64

        from .job_driver import deadline_request_timeout

        if acquired is not None:
            # recompute the lease budget AT CALL TIME: the staging +
            # device phases (and, pipelined, the stage queues) consumed
            # arbitrary wall time since the step captured its budget —
            # an expired lease raises here and steps back instead of
            # dialing the helper on a dead budget. Clamped to the
            # ambient step scope so a DB-clock-granularity recompute
            # can never EXTEND past the bound the watchdog enforced.
            deadline = self._lease_deadline(acquired)
            ambient = current_deadline()
            if ambient is not None:
                deadline = min(deadline, ambient)

        url = (
            task.helper_aggregator_endpoint.rstrip("/")
            + f"/tasks/{base64.urlsafe_b64encode(task.task_id.data).decode().rstrip('=')}"
            + f"/aggregation_jobs/{base64.urlsafe_b64encode(job_id.data).decode().rstrip('=')}"
        )
        headers = {"Content-Type": req.MEDIA_TYPE, **(extra_headers or {})}
        if task.aggregator_auth_token:
            headers.update(task.aggregator_auth_token.request_headers())
        peer = peer_label(task.helper_aggregator_endpoint)
        if self.peer_health is not None:
            # register the endpoint BEFORE any attempt: the tracker can
            # aim its half-open probes even at a peer that never once
            # answered (first contact during an outage)
            self.peer_health.observe_endpoint(task.helper_aggregator_endpoint)
        payload = req.to_bytes()  # encode once, not once per retry attempt

        def attempt():
            # circuit gate per ATTEMPT: a breaker opened by a concurrent
            # step aborts this retry loop too (CircuitOpenError is not a
            # transport error, so retry_http_request lets it propagate)
            self.breakers.check(peer)
            # go through put/post (not request) so test doubles that
            # wrap those verbs see the traffic; the trailing headers
            # element lets a shedding helper's Retry-After pace retries
            fn = self.http.put if method == "PUT" else self.http.post
            try:
                status, body = fn(
                    url, payload, headers, timeout=deadline_request_timeout(deadline)
                )
            except BaseException:
                # transport failure (or anything else before a response):
                # the breaker must learn of it AND free a half-open probe
                self.breakers.record_failure(peer)
                raise
            # 5xx = the peer is failing; anything conclusive (2xx/4xx,
            # incl. problem documents) or shedding (429) = alive
            if 500 <= status < 600:
                self.breakers.record_failure(peer)
            else:
                self.breakers.record_success(peer)
            return status, body, getattr(self.http, "last_response_headers", {})

        status, body = retry_http_request(
            attempt,
            self.cfg.http_backoff,
            deadline=deadline,
            should_abort=(lambda: self.stopper.stopped) if self.stopper is not None else None,
        )
        if status == DEADLINE_EXCEEDED_STATUS:
            # the helper's conclusive "your budget is dead" answer
            # (docs/ROBUSTNESS.md deadline contract): step back, don't
            # fail the job and don't retry against the same dead budget
            raise DeadlineExceeded(
                "helper reported deadline exceeded", last_status=status
            )
        if status not in (200, 201):
            raise RuntimeError(
                f"helper {method} aggregation job failed: HTTP {status}: {body[:300]!r}"
            )
        return body

    def _send_init_request(
        self, task: Task, job_id, req: AggregationJobInitializeReq, deadline: float | None = None,
        acquired=None,
    ) -> AggregationJobResp:
        return AggregationJobResp.from_bytes(
            self._send_init_request_raw(
                task, job_id, req, deadline=deadline, acquired=acquired
            )
        )

    def _send_init_request_raw(
        self, task: Task, job_id, req: AggregationJobInitializeReq, deadline: float | None = None,
        acquired=None,
    ) -> bytes:
        from .http_handlers import XOF_MODE_HEADER

        return self._send_agg_job_request_raw(
            task,
            job_id,
            "PUT",
            req,
            extra_headers={XOF_MODE_HEADER: task.vdaf.xof_mode},
            deadline=deadline,
            acquired=acquired,
        )

    # --- abandon (reference :728) ---
    def abandon_job(self, acquired: AcquiredAggregationJob) -> None:
        def cancel(tx):
            job = tx.get_aggregation_job(acquired.task_id, acquired.job_id)
            if job is None:
                return
            tx.update_aggregation_job(job.with_state(AggregationJobState.ABANDONED))
            ras = tx.get_report_aggregations_for_job(acquired.task_id, acquired.job_id)
            tx.mark_reports_unaggregated(
                acquired.task_id,
                [ra.report_id for ra in ras if ra.state == ReportAggregationState.START],
            )
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(cancel, "abandon_agg_job")
        metrics.job_cancel_counter.add(kind="aggregation")
        log.warning("abandoned aggregation job %s after max attempts", acquired.job_id)


class ResidentFlusher:
    """Background resident-state flusher (driver binary, resident mode):
    every flush_interval_s it pushes dirty resident buffers of every
    live engine through the driver's write-tx flush path, so an IDLE
    driver's last job doesn't sit unflushed until the next job arrives,
    and a QUARANTINED engine's state flushes within one pass (the
    interim host engine's jobs then see the complete batch rows — the
    quarantine-mid-job contract). stop() + a final flush is the drain
    hook (the binary calls driver.flush_resident_state("drain") after
    the job loop exits)."""

    def __init__(self, driver: AggregationJobDriver, interval_s: float):
        self.driver = driver
        self.interval_s = max(0.1, float(interval_s))
        # quarantine sweep cadence: a quarantined engine's resident
        # state must flush within ~a second, NOT within the interval
        # cadence — the interim host engine's jobs read the batch rows
        self.poll_s = min(1.0, self.interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="resident-flusher", daemon=True
        )

    def start(self) -> "ResidentFlusher":
        self._thread.start()
        return self

    def _loop(self) -> None:
        from .engine_cache import live_engines

        elapsed = 0.0
        while not self._stop.wait(self.poll_s):
            elapsed += self.poll_s
            try:
                if elapsed >= self.interval_s:
                    elapsed = 0.0
                    self.driver.flush_resident_state(reason="interval")
                else:
                    for eng in live_engines():
                        if not eng.resident_ready():
                            self.driver.flush_engine_resident(
                                eng, reason="quarantine"
                            )
            except Exception:
                log.exception("resident flush pass failed; retrying next pass")

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout_s)
