"""Aggregation job driver (leader stepper) — the hot path.

Equivalent of reference aggregator/src/aggregator/aggregation_job_driver.rs:
49-894: acquire leases, read job + report state, run leader prepare,
PUT the init request to the helper, process its response, accumulate,
write back, release. The reference's three per-report loops
(leader_initialized :329-402, transition evaluation :467-496,
leader_continued + accumulate :530-726) are each one batched device
call here.

For the 1-round Prio3 VDAFs the whole job completes in a single step:
init -> helper responds finish/reject per report -> leader verifies the
prep message (joint-rand seed equality, host-side lane compare) ->
masked accumulate. Crash anywhere before the final write leaves the
job in step 0 with reports in START; the re-acquired lease replays the
init idempotently (helper request-hash dedup).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from ..core.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
    OutboundCircuitBreakers,
    default_breakers,
    peer_label,
)
from ..core.deadline import DEADLINE_EXCEEDED_STATUS, DeadlineExceeded, deadline_scope
from ..core.retries import Backoff, RequestAborted, retry_http_request
from ..datastore.models import (
    AcquiredAggregationJob,
    AggregationJobState,
    ReportAggregationState,
)
from .. import metrics
from ..datastore.store import Datastore
from ..messages import (
    AggregationJobContinueReq,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    Duration,
    PartialBatchSelector,
    PrepareContinue,
    PrepareError,
    PrepareInit,
    PrepareStepResult,
    ReportShare,
    ReportMetadata,
)
from ..messages.codec import DecodeError
from ..task import Task
from ..vdaf.registry import circuit_for
from ..vdaf.wire import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    Prio3Wire,
    decode_field_rows,
    decode_pingpong,
    encode_field_rows,
    encode_pingpong,
    seeds_to_lanes,
)
from .accumulator import Accumulator, accumulate_batched, fixed_size_batch_id
from .engine_cache import DeviceHangError, engine_cache

log = logging.getLogger(__name__)


def _err_or_default(err) -> PrepareError:
    """PrepareError.BATCH_COLLECTED has enum value 0 (falsy), so the
    `err or DEFAULT` idiom silently rewrites it; compare against None."""
    return err if err is not None else PrepareError.VDAF_PREP_ERROR


@dataclass
class AggregationJobDriverConfig:
    batch_aggregation_shard_count: int = 1
    maximum_attempts_before_failure: int = 10
    http_backoff: Backoff = Backoff()
    # helper HTTP work is bounded by lease remaining minus this skew
    # (reference job_driver.rs:191-196) so a hung helper can't outlive
    # the lease and run the job concurrently with a re-acquirer
    worker_lease_clock_skew_s: int = 60
    # leader->helper outbound circuit breaker (core/circuit_breaker.py;
    # YAML outbound_circuit_breaker: section)
    circuit_breaker: CircuitBreakerConfig | None = None
    # floor for the breaker-open step-back reacquire delay so a job
    # whose cooldown is nearly over doesn't spin acquire/step-back
    min_step_back_delay_s: int = 1


class AggregationJobDriver:
    """reference aggregation_job_driver.rs:49."""

    def __init__(
        self,
        ds: Datastore,
        http,
        cfg: AggregationJobDriverConfig | None = None,
        breakers: OutboundCircuitBreakers | None = None,
        stopper=None,
    ):
        self.ds = ds
        self.http = http
        self.cfg = cfg or AggregationJobDriverConfig()
        # per-peer circuit breaker shared process-wide by default (the
        # collection driver sees the same helper health)
        self.breakers = (
            breakers if breakers is not None else default_breakers(self.cfg.circuit_breaker)
        )
        # shutdown Stopper: in-flight helper retries abort on SIGTERM so
        # the step can step back instead of spending the whole lease
        self.stopper = stopper

    # --- JobDriver callbacks (reference :840-894) ---
    def acquirer(self, lease_duration_s: int = 600):
        from .job_driver import acquire_tolerating_outage

        def acquire(limit: int):
            return acquire_tolerating_outage(
                self.ds,
                lambda: self.ds.run_tx(
                    lambda tx: tx.acquire_incomplete_aggregation_jobs(
                        Duration(lease_duration_s), limit
                    ),
                    "acquire_agg_jobs",
                ),
            )

        return acquire

    def _lease_deadline(self, acquired) -> float:
        from .job_driver import lease_deadline

        return lease_deadline(
            self.ds.clock, acquired.lease, self.cfg.worker_lease_clock_skew_s
        )

    def stepper(self, acquired: AcquiredAggregationJob) -> None:
        if acquired.lease.attempts > self.cfg.maximum_attempts_before_failure:
            self.abandon_job(acquired)
            return
        try:
            self.step_aggregation_job(acquired)
        except CircuitOpenError as e:
            # the helper's circuit is open: not this job's fault — step
            # back (release the lease with the cooldown as backoff,
            # refund the attempt) instead of failing the step
            self.step_back(
                acquired,
                "circuit_open",
                max(e.retry_in_s, self.cfg.min_step_back_delay_s),
            )
        except RequestAborted:
            # shutdown drain: hand the lease back immediately
            self.step_back(acquired, "shutdown_drain", 0.0)
        except DeadlineExceeded:
            # the lease budget died (expired lease, retry loop past the
            # bound, or the helper answered the conclusive 408): dead
            # work is dropped here and redone under a fresh lease —
            # never amplified by burning the attempt ledger
            self.step_back(acquired, "deadline_expired", 0.0)
        except DeviceHangError:
            # the device dispatch hung and was abandoned; the engine is
            # quarantined (host fallback serves the retry) — not this
            # job's fault, step back with a short reacquire delay
            self.step_back(
                acquired, "device_hang", self.cfg.min_step_back_delay_s
            )
        except Exception as e:
            from .job_driver import datastore_reconnect_delay_s, is_datastore_connection_error

            if is_datastore_connection_error(self.ds, e):
                # datastore outage mid-step: not this job's fault —
                # step back with the reconnect cooldown (best effort;
                # if the step-back tx also fails, the lease ages out)
                self.step_back(
                    acquired, "datastore_down", datastore_reconnect_delay_s(self.ds)
                )
                return
            log.exception(
                "aggregation job %s step failed (attempt %d)",
                acquired.job_id,
                acquired.lease.attempts,
            )
            raise

    def step_back(
        self, acquired: AcquiredAggregationJob, reason: str, delay_s: float
    ) -> None:
        """Release the lease early (reacquirable after delay_s, attempt
        refunded) — a breaker-open helper or a draining process must
        neither burn lease TTLs nor march the job toward abandonment."""
        from ..datastore.store import TxConflict

        delay = max(0, int(delay_s))
        log.warning(
            "stepping back aggregation job %s (%s): lease released, reacquirable in %ds",
            acquired.job_id, reason, delay,
        )
        metrics.job_step_back_total.add(reason=reason)
        try:
            self.ds.run_tx(
                lambda tx: tx.step_back_aggregation_job(
                    acquired, reacquire_delay_s=delay, count_attempt=False
                ),
                "step_back_agg_job",
            )
        except TxConflict:
            # lease already lost (expired / re-acquired): nothing to return
            log.info("step-back of %s found the lease already gone", acquired.job_id)
        except Exception:
            # datastore unreachable: the lease ages out on its own TTL —
            # the step-back is an optimization, never a correctness need
            log.warning(
                "step-back of %s could not reach the datastore; lease will age out",
                acquired.job_id,
            )

    def _stage_pending(self, task, wire, engine, pending, reports):
        """Columnar staging of stored leader shares -> device-ready
        arrays + per-report failure marks."""
        n = len(pending)
        meas_rows: list[bytes | None] = [None] * n
        proof_rows: list[bytes | None] = [None] * n
        blind_rows: list[bytes | None] = [None] * n
        part_rows0: list[bytes | None] = [None] * n
        part_rows1: list[bytes | None] = [None] * n
        failed = [None] * n  # PrepareError or None
        circ = wire.circ
        mlen = circ.input_len * wire.enc_size
        plen = circ.proof_len * wire.enc_size
        for i, ra in enumerate(pending):
            rep = reports.get(ra.report_id.data)
            if rep is None:
                failed[i] = PrepareError.REPORT_DROPPED
                continue
            payload = rep.leader_input_share
            if len(payload) != wire.leader_share_len:
                failed[i] = PrepareError.INVALID_MESSAGE
                continue
            meas_rows[i] = payload[:mlen]
            proof_rows[i] = payload[mlen : mlen + plen]
            if wire.uses_jr:
                blind_rows[i] = payload[mlen + plen :]
                try:
                    parts = wire.decode_public_share(rep.public_share)
                    part_rows0[i], part_rows1[i] = parts
                except DecodeError:
                    failed[i] = PrepareError.INVALID_MESSAGE

        # test-only fake failure injection on the leader init path
        # (the reference's dummy_vdaf prep_init_fn hook)
        if task.vdaf.fails_at("init"):
            for i in range(n):
                if failed[i] is None:
                    failed[i] = PrepareError.VDAF_PREP_ERROR

        jf = engine.p3.jf
        meas, ok_m = decode_field_rows(jf, meas_rows, circ.input_len)
        proof, ok_p = decode_field_rows(jf, proof_rows, circ.proof_len)
        nonce_lanes, _ = seeds_to_lanes([ra.report_id.data for ra in pending])
        ok = ok_m & ok_p & np.array([f is None for f in failed])
        if wire.uses_jr:
            blind_lanes, ok_b = seeds_to_lanes(blind_rows)
            p0, ok_p0 = seeds_to_lanes(part_rows0)
            p1, ok_p1 = seeds_to_lanes(part_rows1)
            ok = ok & ok_b & ok_p0 & ok_p1
            public_parts = np.stack([p0, p1], axis=1)
        else:
            blind_lanes = None
            public_parts = None
        return meas, proof, nonce_lanes, blind_lanes, public_parts, ok, failed

    # --- the step (reference :102-726) ---
    def step_aggregation_job(self, acquired: AcquiredAggregationJob) -> None:
        # tx1: read everything (reference :144-233)
        def read(tx):
            task = tx.get_task(acquired.task_id)
            job = tx.get_aggregation_job(acquired.task_id, acquired.job_id)
            ras = tx.get_report_aggregations_for_job(acquired.task_id, acquired.job_id)
            reports = {}
            for ra in ras:
                if ra.state == ReportAggregationState.START:
                    reports[ra.report_id.data] = tx.get_client_report(
                        acquired.task_id, ra.report_id
                    )
            return task, job, ras, reports

        from ..trace import span, use_traceparent

        with span("driver.read_tx"):
            task, job, ras, reports = self.ds.run_tx(read, "step_agg_job_read")
        if job is None or task is None:
            raise RuntimeError("job or task vanished while leased")
        if job.state != AggregationJobState.IN_PROGRESS:
            self.ds.run_tx(lambda tx: tx.release_aggregation_job(acquired), "release")
            return

        # adopt the trace the job's CREATOR persisted in the row: every
        # span below (stage/encode/http/engine/write — and the helper's
        # handler spans, via the propagated traceparent header) joins
        # that trace, no matter which driver process steps the job or
        # how many restarts separate the steps. The lease budget rides
        # the same scope (core/deadline.py): the engine watchdog bounds
        # device dispatches with it and the HTTP client stamps the
        # remainder on outbound helper requests (DAP-Janus-Deadline).
        with use_traceparent(job.trace_context), deadline_scope(
            self._lease_deadline(acquired)
        ):
            self._step_leased_job(acquired, task, job, ras, reports)

    def _step_leased_job(self, acquired, task, job, ras, reports) -> None:
        from ..trace import span

        # multi-round jobs park accepted reports in WaitingLeader after
        # init; a later step sends the continue request (reference
        # :439-514 CONTINUE path)
        waiting = [ra for ra in ras if ra.state == ReportAggregationState.WAITING_LEADER]
        if waiting:
            self._continue_step(acquired, task, job, waiting)
            return

        pending = [ra for ra in ras if ra.state == ReportAggregationState.START]
        if task.vdaf.kind == "poplar1":
            self._step_poplar1_init(acquired, task, job, pending, reports)
            return

        wire = Prio3Wire(circuit_for(task.vdaf))
        engine = engine_cache(task.vdaf, task.vdaf_verify_key)
        if not pending:
            # nothing to do; mark job finished
            def finish_empty(tx):
                tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
                tx.release_aggregation_job(acquired)

            self.ds.run_tx(finish_empty, "step_agg_job_finish_empty")
            return

        # columnar staging of stored leader shares
        n = len(pending)
        with span("driver.stage", batch=n):
            (
                meas,
                proof,
                nonce_lanes,
                blind_lanes,
                public_parts,
                ok,
                failed,
            ) = self._stage_pending(task, wire, engine, pending, reports)
        jf = engine.p3.jf

        # device: batched leader prepare-init (reference hot loop :329-402)
        out0, seed0, ver0, part0 = engine.leader_init(
            nonce_lanes, public_parts, meas, proof, blind_lanes, ok=ok
        )

        # build + send the init request (reference :404-424)
        with span("driver.encode_init", batch=n):
            ver0_rows = encode_field_rows(jf, ver0)
            part0_rows = (
                [row.tobytes() for row in np.asarray(part0, dtype="<u8")]
                if wire.uses_jr
                else [None] * n
            )
            prep_inits = []
            send_idx = []
            for i, ra in enumerate(pending):
                if failed[i] is not None or not ok[i]:
                    if failed[i] is None:
                        failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                rep = reports[ra.report_id.data]
                prep_share = wire.encode_prep_share_raw(ver0_rows[i], part0_rows[i])
                prep_inits.append(
                    PrepareInit(
                        ReportShare(
                            ReportMetadata(ra.report_id, ra.client_time),
                            rep.public_share,
                            rep.helper_encrypted_input_share,
                        ),
                        encode_pingpong(PP_INITIALIZE, None, prep_share),
                    )
                )
                send_idx.append(i)

        multi_round = task.vdaf.rounds > 1
        accept = np.zeros(n, dtype=bool)
        continue_msgs: list[bytes | None] = [None] * n
        if prep_inits:
            req = AggregationJobInitializeReq(
                job.aggregation_parameter,
                PartialBatchSelector.from_bytes(job.partial_batch_identifier),
                tuple(prep_inits),
            )
            with span("driver.http_init", reports=len(prep_inits)):
                resp = self._send_init_request(
                    task, acquired.job_id, req, deadline=self._lease_deadline(acquired)
                )
            by_id = {pr.report_id: pr for pr in resp.prepare_resps}
            # process response (reference :530-726), host-side lane checks
            for k, i in enumerate(send_idx):
                ra = pending[i]
                pr = by_id.get(ra.report_id)
                if pr is None:
                    failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                if pr.result.kind == PrepareStepResult.REJECT:
                    failed[i] = _err_or_default(pr.result.prepare_error)
                    continue
                if pr.result.kind not in (PrepareStepResult.CONTINUE, PrepareStepResult.FINISHED):
                    failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                if multi_round:
                    # helper answered ping-pong CONTINUE; the leader's
                    # next message (sent on a later step) finishes with
                    # the combined prep message (fake: echo)
                    try:
                        tag, prep_msg, _share = decode_pingpong(pr.result.message)
                    except DecodeError:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    if tag != PP_CONTINUE:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    continue_msgs[i] = encode_pingpong(PP_FINISH, prep_msg or b"", None)
                    accept[i] = True
                    continue
                if wire.uses_jr:
                    try:
                        tag, prep_msg, _ = decode_pingpong(pr.result.message)
                    except DecodeError:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    if tag != PP_FINISH or prep_msg is None or len(prep_msg) != 16:
                        failed[i] = PrepareError.INVALID_MESSAGE
                        continue
                    want = np.asarray(seed0[i], dtype="<u8").tobytes()
                    if prep_msg != want:
                        failed[i] = PrepareError.VDAF_PREP_ERROR
                        continue
                accept[i] = True

        # test-only fake failure at the leader continue/evaluate stage
        # (the reference's dummy_vdaf prep_step_fn hook)
        if task.vdaf.fails_at("step"):
            for i in range(n):
                if accept[i]:
                    accept[i] = False
                    failed[i] = PrepareError.VDAF_PREP_ERROR

        if multi_round:
            # park accepted reports as WaitingLeader(out_share || msg);
            # job stays in progress — a later driver step sends the
            # continue request (reference stores the transition the same
            # way, models.rs:714 WaitingLeader)
            import dataclasses

            out0_rows = encode_field_rows(jf, out0)
            new_ras = []
            for i, ra in enumerate(pending):
                if accept[i]:
                    msg = continue_msgs[i]
                    blob = len(msg).to_bytes(4, "big") + msg + out0_rows[i]
                    new_ras.append(
                        dataclasses.replace(
                            ra,
                            state=ReportAggregationState.WAITING_LEADER,
                            prep_blob=blob,
                        )
                    )
                else:
                    err = _err_or_default(failed[i])
                    metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                    new_ras.append(ra.failed(err))

            def write_waiting(tx):
                for ra in new_ras:
                    tx.update_report_aggregation(ra)
                tx.release_aggregation_job(acquired)

            self.ds.run_tx(write_waiting, "step_agg_job_park")
            return

        # masked accumulate (reference Accumulator::update :605-627)
        accumulator = Accumulator(task, self.cfg.batch_aggregation_shard_count)
        metadatas = [ReportMetadata(ra.report_id, ra.client_time) for ra in pending]
        pbs = PartialBatchSelector.from_bytes(job.partial_batch_identifier)
        fixed_bid = fixed_size_batch_id(pbs)
        with span("driver.accumulate", batch=n):
            accumulate_batched(
                task, engine, accumulator, out0, accept, metadatas, batch_identifier=fixed_bid
            )

        # tx2: write results + release (reference :698-724)
        new_ras = []
        for i, ra in enumerate(pending):
            if accept[i]:
                new_ras.append(ra.finished())
            else:
                err = _err_or_default(failed[i])
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        # committing attempt's unmergeable set, carried out of the tx for
        # the post-commit e2e observation (run_tx may retry the closure)
        cell: dict = {}

        def write(tx):
            # flush first: reports whose batch was collected mid-flight
            # fail individually with BATCH_COLLECTED (reference
            # flush_to_datastore unmergeable set, accumulator.rs:133-215)
            unmerged = accumulator.flush_to_datastore(tx)
            cell["unmerged"] = unmerged
            for ra in new_ras:
                if ra.report_id.data in unmerged:
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                tx.update_report_aggregation(ra)
            tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
            tx.release_aggregation_job(acquired)

        with span("driver.write_tx", batch=n):
            self.ds.run_tx(write, "step_agg_job_write")
        # e2e SLO observed only AFTER the write committed: a failed step
        # retried under a fresh lease must not leave phantom samples
        from .accumulator import observe_finished_report_e2e

        observe_finished_report_e2e(self.ds.clock, new_ras, cell.get("unmerged", ()))

    def _step_poplar1_init(self, acquired, task: Task, job, pending, reports) -> None:
        """Poplar1 leader init (see aggregator.poplar1_ops docstring):
        evaluate IDPF shares at the job's aggregation parameter, send
        sketch shares, verify the helper's combined sketch, park
        WaitingLeader for the continue round."""
        import dataclasses

        from .poplar1_ops import Poplar1Ops

        pop = Poplar1Ops(task.vdaf.bits, task.vdaf_verify_key)
        param = pop.decode_param(job.aggregation_parameter)
        F = pop.field_for(param)

        if not pending:
            def finish_empty(tx):
                tx.update_aggregation_job(job.with_state(AggregationJobState.FINISHED))
                tx.release_aggregation_job(acquired)

            self.ds.run_tx(finish_empty, "step_p1_job_finish_empty")
            return

        n = len(pending)
        failed: list = [None] * n
        evals: dict[int, tuple] = {}  # i -> (prep state, y0, [A0, B0])
        items = []
        item_idx = []
        for i, ra in enumerate(pending):
            rep = reports.get(ra.report_id.data)
            if rep is None:
                failed[i] = PrepareError.REPORT_DROPPED
                continue
            items.append(
                (rep.public_share, rep.leader_input_share, ra.report_id.data)
            )
            item_idx.append(i)
        # one batched device IDPF walk + sketch for the whole job
        for i, res in zip(item_idx, pop.round1_batch(0, items, param)):
            if isinstance(res, ValueError):
                failed[i] = PrepareError.INVALID_MESSAGE
            else:
                evals[i] = res

        prep_inits = []
        send_idx = []
        for i, ra in enumerate(pending):
            if failed[i] is not None:
                continue
            rep = reports[ra.report_id.data]
            _, _, msg1_0 = evals[i]
            prep_inits.append(
                PrepareInit(
                    ReportShare(
                        ReportMetadata(ra.report_id, ra.client_time),
                        rep.public_share,
                        rep.helper_encrypted_input_share,
                    ),
                    encode_pingpong(PP_INITIALIZE, None, pop.encode_vec(param, msg1_0)),
                )
            )
            send_idx.append(i)

        parked: dict[int, bytes] = {}  # i -> WaitingLeader blob
        if prep_inits:
            req = AggregationJobInitializeReq(
                job.aggregation_parameter,
                PartialBatchSelector.from_bytes(job.partial_batch_identifier),
                tuple(prep_inits),
            )
            resp = self._send_init_request(
                task, acquired.job_id, req, deadline=self._lease_deadline(acquired)
            )
            by_id = {pr.report_id: pr for pr in resp.prepare_resps}
            for i in send_idx:
                ra = pending[i]
                pr = by_id.get(ra.report_id)
                if pr is None or pr.result.kind == PrepareStepResult.REJECT:
                    failed[i] = _err_or_default(
                        pr.result.prepare_error if pr is not None else None
                    )
                    continue
                try:
                    tag, prep_msg, helper_share = decode_pingpong(pr.result.message)
                    if tag != PP_CONTINUE or helper_share is None:
                        raise DecodeError("expected ping-pong continue")
                    es = pop.enc_size(param)
                    # helper share = enc(A1)||enc(B1)||enc(sigma1)
                    msg1_1 = pop.decode_fixed_vec(param, helper_share[: 2 * es], 2)
                    sigma1 = pop.decode_elem(param, helper_share[2 * es :])
                except (DecodeError, ValueError):
                    failed[i] = PrepareError.INVALID_MESSAGE
                    continue
                st0, y0, msg1_0 = evals[i]
                sigma0, combined = pop.round2(st0, msg1_0, msg1_1)
                # the helper's claimed round-1 prep message must equal our
                # own combination, and the quadratic sketch must verify
                # (sigma0 + sigma1 == 0 <=> y one-hot or all-zero)
                if prep_msg != pop.encode_vec(param, combined) or F.add(
                    sigma0, sigma1
                ) != 0:
                    failed[i] = PrepareError.VDAF_PREP_ERROR
                    continue
                msg = encode_pingpong(PP_FINISH, pop.encode_elem(param, sigma0), None)
                parked[i] = (
                    len(msg).to_bytes(4, "big") + msg + pop.encode_vec(param, y0)
                )

        new_ras = []
        for i, ra in enumerate(pending):
            if i in parked:
                new_ras.append(
                    dataclasses.replace(
                        ra,
                        state=ReportAggregationState.WAITING_LEADER,
                        prep_blob=parked[i],
                    )
                )
            else:
                err = _err_or_default(failed[i])
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        def write_waiting(tx):
            for ra in new_ras:
                tx.update_report_aggregation(ra)
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(write_waiting, "step_p1_job_park")

    def _continue_step(self, acquired, task: Task, job, waiting) -> None:
        """Send the ord-matched continue request for WaitingLeader rows
        and finish the job (reference :439-514 + :530-726)."""
        import dataclasses

        if task.vdaf.kind == "poplar1":
            from .poplar1_ops import Poplar1Ops

            pop = Poplar1Ops(task.vdaf.bits)
            field = pop.field_for(pop.decode_param(job.aggregation_parameter))
        else:
            field = circuit_for(task.vdaf).FIELD
        msgs = []
        outs = []
        for ra in waiting:
            mlen = int.from_bytes(ra.prep_blob[:4], "big")
            msgs.append(ra.prep_blob[4 : 4 + mlen])
            outs.append(ra.prep_blob[4 + mlen :])
        req = AggregationJobContinueReq(
            AggregationJobStep(job.step + 1),
            tuple(
                PrepareContinue(ra.report_id, msg) for ra, msg in zip(waiting, msgs)
            ),
        )
        from ..trace import span

        with span("driver.http_continue", reports=len(waiting)):
            resp = self._send_continue_request(
                task, acquired.job_id, req, deadline=self._lease_deadline(acquired)
            )
        by_id = {pr.report_id: pr for pr in resp.prepare_resps}

        accumulator = Accumulator(
            task,
            self.cfg.batch_aggregation_shard_count,
            field=field,
            aggregation_parameter=job.aggregation_parameter,
        )
        pbs = PartialBatchSelector.from_bytes(job.partial_batch_identifier)
        fixed_bid = fixed_size_batch_id(pbs)
        new_ras = []
        for ra, out_enc in zip(waiting, outs):
            pr = by_id.get(ra.report_id)
            if pr is not None and pr.result.kind == PrepareStepResult.FINISHED:
                from ..messages import Interval

                bid = fixed_bid or Interval(
                    ra.client_time.to_batch_interval_start(task.time_precision),
                    task.time_precision,
                ).to_bytes()
                accumulator.update_single(
                    bid, field.decode_vec(out_enc), ra.report_id, ra.client_time
                )
                new_ras.append(
                    dataclasses.replace(
                        ra, state=ReportAggregationState.FINISHED, prep_blob=b""
                    )
                )
            else:
                err = _err_or_default(
                    pr.result.prepare_error
                    if pr is not None and pr.result.kind == PrepareStepResult.REJECT
                    else None
                )
                metrics.aggregate_step_failure_counter.add(type=err.name.lower())
                new_ras.append(ra.failed(err))

        new_job = dataclasses.replace(
            job, state=AggregationJobState.FINISHED, step=job.step + 1
        )
        cell: dict = {}

        def write(tx):
            unmerged = accumulator.flush_to_datastore(tx)
            cell["unmerged"] = unmerged
            for ra in new_ras:
                if ra.report_id.data in unmerged:
                    ra = ra.failed(PrepareError.BATCH_COLLECTED)
                tx.update_report_aggregation(ra)
            tx.update_aggregation_job(new_job)
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(write, "step_agg_job_continue_write")
        # e2e SLO observed only post-commit (see the init path above)
        from .accumulator import observe_finished_report_e2e

        observe_finished_report_e2e(self.ds.clock, new_ras, cell.get("unmerged", ()))

    def _send_continue_request(
        self, task: Task, job_id, req: AggregationJobContinueReq, deadline: float | None = None
    ) -> AggregationJobResp:
        return self._send_agg_job_request(task, job_id, "POST", req, deadline=deadline)

    def _send_agg_job_request(
        self,
        task: Task,
        job_id,
        method: str,
        req,
        extra_headers: dict | None = None,
        deadline: float | None = None,
    ) -> AggregationJobResp:
        """Shared PUT(init)/POST(continue) to the helper's
        aggregation_jobs endpoint: URL, auth, deadline-capped timeouts,
        retries, response decode."""
        import base64

        from .job_driver import deadline_request_timeout

        url = (
            task.helper_aggregator_endpoint.rstrip("/")
            + f"/tasks/{base64.urlsafe_b64encode(task.task_id.data).decode().rstrip('=')}"
            + f"/aggregation_jobs/{base64.urlsafe_b64encode(job_id.data).decode().rstrip('=')}"
        )
        headers = {"Content-Type": req.MEDIA_TYPE, **(extra_headers or {})}
        if task.aggregator_auth_token:
            headers.update(task.aggregator_auth_token.request_headers())
        peer = peer_label(task.helper_aggregator_endpoint)

        def attempt():
            # circuit gate per ATTEMPT: a breaker opened by a concurrent
            # step aborts this retry loop too (CircuitOpenError is not a
            # transport error, so retry_http_request lets it propagate)
            self.breakers.check(peer)
            # go through put/post (not request) so test doubles that
            # wrap those verbs see the traffic; the trailing headers
            # element lets a shedding helper's Retry-After pace retries
            fn = self.http.put if method == "PUT" else self.http.post
            try:
                status, body = fn(
                    url, req.to_bytes(), headers, timeout=deadline_request_timeout(deadline)
                )
            except BaseException:
                # transport failure (or anything else before a response):
                # the breaker must learn of it AND free a half-open probe
                self.breakers.record_failure(peer)
                raise
            # 5xx = the peer is failing; anything conclusive (2xx/4xx,
            # incl. problem documents) or shedding (429) = alive
            if 500 <= status < 600:
                self.breakers.record_failure(peer)
            else:
                self.breakers.record_success(peer)
            return status, body, getattr(self.http, "last_response_headers", {})

        status, body = retry_http_request(
            attempt,
            self.cfg.http_backoff,
            deadline=deadline,
            should_abort=(lambda: self.stopper.stopped) if self.stopper is not None else None,
        )
        if status == DEADLINE_EXCEEDED_STATUS:
            # the helper's conclusive "your budget is dead" answer
            # (docs/ROBUSTNESS.md deadline contract): step back, don't
            # fail the job and don't retry against the same dead budget
            raise DeadlineExceeded(
                "helper reported deadline exceeded", last_status=status
            )
        if status not in (200, 201):
            raise RuntimeError(
                f"helper {method} aggregation job failed: HTTP {status}: {body[:300]!r}"
            )
        return AggregationJobResp.from_bytes(body)

    def _send_init_request(
        self, task: Task, job_id, req: AggregationJobInitializeReq, deadline: float | None = None
    ) -> AggregationJobResp:
        from .http_handlers import XOF_MODE_HEADER

        return self._send_agg_job_request(
            task,
            job_id,
            "PUT",
            req,
            extra_headers={XOF_MODE_HEADER: task.vdaf.xof_mode},
            deadline=deadline,
        )

    # --- abandon (reference :728) ---
    def abandon_job(self, acquired: AcquiredAggregationJob) -> None:
        def cancel(tx):
            job = tx.get_aggregation_job(acquired.task_id, acquired.job_id)
            if job is None:
                return
            tx.update_aggregation_job(job.with_state(AggregationJobState.ABANDONED))
            ras = tx.get_report_aggregations_for_job(acquired.task_id, acquired.job_id)
            tx.mark_reports_unaggregated(
                acquired.task_id,
                [ra.report_id for ra in ras if ra.state == ReportAggregationState.START],
            )
            tx.release_aggregation_job(acquired)

        self.ds.run_tx(cancel, "abandon_agg_job")
        metrics.job_cancel_counter.add(kind="aggregation")
        log.warning("abandoned aggregation job %s after max attempts", acquired.job_id)
