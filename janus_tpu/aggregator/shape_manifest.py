"""Persisted shape manifest: the dispatch specializations this process
has actually compiled (docs/ARCHITECTURE.md "Cold-start and prewarm").

Every first dispatch of a jit specialization — (vdaf config, op, batch
bucket, compile_key: the variant name plus any extra geometry such as
aggregate_pending's padded bucket count kk) — is recorded here by the
EngineCache choke point (`_record_dispatch`), together with the wall
time that first call cost (trace + XLA compile + execute: exactly the
cold-start price a restarted process would pay again). At the next
boot the prewarm engine (aggregator/prewarm.py) replays the manifest
highest-cost-first against the provisioned tasks, so the persistent
XLA compile cache is loaded and every observed specialization is
traced BEFORE /readyz reports ready.

File format: append-only JSONL, one record per line:

    {"v": 1, "crc": <crc32 of canonical entry json>, "e": {entry}}

entry = {vdaf: VdafInstance.to_dict(), op, bucket, key: [compile_key],
cost_s, rows, seen, last_unix}. The discipline mirrors the upload
journal's (ingest/journal.py), scaled down for advisory data:

  * **Torn tails tolerated**: a crash mid-append leaves a truncated
    final line; it fails to parse and is skipped (counted), the valid
    prefix loads. No fsync — losing a tail entry costs one cold
    compile later, never correctness.
  * **Damage skipped, never fatal**: a line whose CRC or JSON is bad
    is counted and skipped; a corrupt manifest can slow a boot, it
    cannot break one (a manifest-less boot degrades to the legacy
    warmup behavior).
  * **Version skew skipped**: lines with `v` != MANIFEST_VERSION are
    counted and ignored — an old binary's manifest never crashes a
    new one, and vice versa.
  * **Append-compacted and bounded**: repeated boots append duplicate
    keys (later lines win, `seen` sums); once the file grows past
    the compaction threshold it is rewritten (tmp + atomic
    os.replace) with one line per live entry, truncated to
    `max_entries` by recorded cost.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1
DEFAULT_MAX_ENTRIES = 512
DEFAULT_FILENAME = "shape_manifest.jsonl"


def _canonical(entry: dict) -> str:
    return json.dumps(entry, sort_keys=True, separators=(",", ":"))


def _crc(entry: dict) -> int:
    return zlib.crc32(_canonical(entry).encode()) & 0xFFFFFFFF


def entry_key(entry: dict) -> tuple:
    """Identity of one specialization: (vdaf config, op, bucket,
    compile_key). The compile_key list is the jit variant the call
    site specialized (engine_cache._record_dispatch), so e.g.
    aggregate_pending's kk geometry keys separately per kk."""
    return (
        _canonical(entry.get("vdaf") or {}),
        str(entry.get("op", "")),
        int(entry.get("bucket", 0)),
        tuple(entry.get("key") or ()),
    )


def entry_geometry(key) -> tuple[int, int, int] | None:
    """The mesh geometry a specialization was recorded under:
    engine_cache._record_dispatch suffixes mesh compile keys with
    ("mesh", dp, sp, device count). Returns (dp, sp, ndev), or None
    for a single-device entry. Prewarm and the legacy warmup use this
    to skip entries whose topology doesn't match the booting process —
    a single-device boot replaying a (4, 2, 8) program (or vice versa)
    would spend its boot budget tracing programs serving never runs."""
    k = tuple(key or ())
    if len(k) >= 4 and str(k[-4]) == "mesh":
        try:
            return int(k[-3]), int(k[-2]), int(k[-1])
        except (TypeError, ValueError):
            return None
    return None


class ShapeManifest:
    """See the module docstring. Thread-safe: `record` may be called
    from any dispatch thread while `entries`/`status` snapshot for the
    prewarm loop and /statusz."""

    def __init__(
        self,
        path: str,
        max_entries: int = DEFAULT_MAX_ENTRIES,
    ):
        self.path = path
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: dict[tuple, dict] = {}
        self._file_lines = 0
        self._compactions = 0
        self.load_stats = {
            "lines": 0,
            "loaded": 0,
            "skipped_corrupt": 0,
            "skipped_version": 0,
        }

    # -- load ----------------------------------------------------------
    def load(self, compact: bool = True) -> dict:
        """Read the file, tolerant of torn tails / damage / version
        skew (each skipped and counted, valid prefix + suffix load).
        Returns the load stats. A missing file is an empty manifest.
        `compact=False` makes the load strictly read-only (diagnostic
        tools must not rewrite the evidence they capture)."""
        stats = {"lines": 0, "loaded": 0, "skipped_corrupt": 0, "skipped_version": 0}
        entries: dict[tuple, dict] = {}
        lines = 0
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = b""
        except OSError as e:
            log.warning("shape manifest %s unreadable (%s); starting empty", self.path, e)
            raw = b""
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            lines += 1
            stats["lines"] += 1
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict):
                    raise ValueError("not an object")
            except (ValueError, UnicodeDecodeError):
                stats["skipped_corrupt"] += 1
                continue
            if rec.get("v") != MANIFEST_VERSION:
                stats["skipped_version"] += 1
                continue
            entry = rec.get("e")
            if not isinstance(entry, dict) or rec.get("crc") != _crc(entry):
                stats["skipped_corrupt"] += 1
                continue
            try:
                # last line wins: each appended record carries the
                # cumulative seen count, so a replace (not a sum) keeps
                # the append-log semantics across compactions
                entries[entry_key(entry)] = entry
                stats["loaded"] += 1
            except (TypeError, ValueError):
                stats["skipped_corrupt"] += 1
        with self._lock:
            self._entries = entries
            self._file_lines = lines
            self.load_stats = stats
            if compact and (
                stats["skipped_corrupt"]
                or stats["skipped_version"]
                or lines > self._compact_threshold()
                or len(entries) > self.max_entries
            ):
                self._compact_locked()
        if stats["skipped_corrupt"] or stats["skipped_version"]:
            log.warning(
                "shape manifest %s: loaded %d entries, skipped %d corrupt + %d "
                "version-skew line(s)",
                self.path,
                stats["loaded"],
                stats["skipped_corrupt"],
                stats["skipped_version"],
            )
        return dict(stats)

    # -- record --------------------------------------------------------
    def record(
        self,
        vdaf: dict,
        op: str,
        bucket: int,
        compile_key,
        cost_s: float,
        rows: int = 0,
    ) -> None:
        """Record one observed specialization (called at FIRST dispatch
        of a compile_key per process, so the append rate is bounded by
        the number of distinct specializations). `cost_s` is that first
        call's wall time — compile + first execute — which is what the
        prewarm priority order sorts on; re-observations keep the MAX
        recorded cost (a cache-hit re-record must not demote a
        genuinely expensive compile)."""
        entry = {
            "vdaf": dict(vdaf),
            "op": str(op),
            "bucket": int(bucket),
            "key": [
                k if isinstance(k, (int, float)) else str(k)
                for k in (compile_key or (op, bucket))
            ],
            "cost_s": round(float(cost_s), 6),
            "rows": int(rows),
            "seen": 1,
            "last_unix": round(time.time(), 3),
        }
        key = entry_key(entry)
        with self._lock:
            prev = self._entries.get(key)
            if prev is not None:
                entry["seen"] = int(prev.get("seen", 1)) + 1
                entry["cost_s"] = max(entry["cost_s"], float(prev.get("cost_s", 0.0)))
            self._entries[key] = entry
            self._append_locked(entry)
            if (
                self._file_lines > self._compact_threshold()
                or len(self._entries) > self.max_entries
            ):
                self._compact_locked()

    def _append_locked(self, entry: dict) -> None:
        line = (
            json.dumps(
                {"v": MANIFEST_VERSION, "crc": _crc(entry), "e": entry},
                sort_keys=True,
                separators=(",", ":"),
            )
            + "\n"
        )
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)
            self._file_lines += 1
        except OSError:
            log.warning("shape manifest append to %s failed", self.path, exc_info=True)

    def _compact_threshold(self) -> int:
        return max(64, 2 * self.max_entries)

    def _compact_locked(self) -> None:
        """Rewrite the file with one line per live entry, truncated to
        max_entries by cost (tmp + atomic replace: a crash leaves either
        the old file or the new one, never a half-written manifest)."""
        keep = sorted(
            self._entries.values(), key=lambda e: -float(e.get("cost_s", 0.0))
        )[: self.max_entries]
        self._entries = {entry_key(e): e for e in keep}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                for e in keep:
                    f.write(
                        json.dumps(
                            {"v": MANIFEST_VERSION, "crc": _crc(e), "e": e},
                            sort_keys=True,
                            separators=(",", ":"),
                        )
                        + "\n"
                    )
            os.replace(tmp, self.path)
            self._file_lines = len(keep)
            self._compactions += 1
        except OSError:
            log.warning("shape manifest compaction of %s failed", self.path, exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- queries -------------------------------------------------------
    def entries(self) -> list[dict]:
        """Snapshot of live entries, highest recorded cost first (the
        prewarm priority order: the most expensive compiles must land
        inside the boot budget)."""
        with self._lock:
            out = [dict(e) for e in self._entries.values()]
        out.sort(key=lambda e: (-float(e.get("cost_s", 0.0)), str(e.get("op", ""))))
        return out

    def covers(
        self,
        vdaf: dict,
        op: str,
        bucket: int,
        geometry: tuple[int, int, int] | None = None,
    ) -> bool:
        """True when a recorded specialization matches (vdaf, op,
        bucket) with the PLAIN jit variant — the legacy warmup uses
        this to skip geometries the manifest-driven prewarm already
        warms. The variant check matters: a manifest holding only
        `leader_init_vk` (cross-task-coalesced) entries must not
        suppress warming the plain `leader_init` program, which is a
        distinct compile the prewarm never touched. `geometry` is the
        caller's (dp, sp, ndev) mesh triple (None = single-device): an
        entry recorded under a DIFFERENT topology must not claim
        coverage — the prewarm will skip it, so warmup still owes the
        compile."""
        vkey = _canonical(dict(vdaf))
        with self._lock:
            return any(
                k[0] == vkey
                and k[1] == str(op)
                and k[2] == int(bucket)
                and k[3]
                and str(k[3][0]) == str(op)
                and entry_geometry(k[3]) == geometry
                for k in self._entries
            )

    def file_bytes(self) -> int:
        try:
            return os.stat(self.path).st_size
        except OSError:
            return 0

    def status(self) -> dict:
        with self._lock:
            n = len(self._entries)
            stats = dict(self.load_stats)
            compactions = self._compactions
            lines = self._file_lines
        return {
            "path": self.path,
            "entries": n,
            "max_entries": self.max_entries,
            "file_lines": lines,
            "file_bytes": self.file_bytes(),
            "compactions": compactions,
            "load": stats,
        }


# ---------------------------------------------------------------------------
# Process-wide installed manifest. janus_main installs it at boot (path
# from the YAML `engine:` stanza, defaulting next to the compile cache)
# and uninstalls at teardown; the EngineCache choke point records into
# whatever is installed (a no-op otherwise, so bench/tests that never
# install one pay a single None check per first-dispatch).
# ---------------------------------------------------------------------------

_installed: ShapeManifest | None = None
_installed_lock = threading.Lock()


def install_manifest(path: str, max_entries: int = DEFAULT_MAX_ENTRIES) -> ShapeManifest:
    """Install (and load) the process shape manifest. Replaces any
    previous instance."""
    global _installed
    m = ShapeManifest(path, max_entries=max_entries)
    m.load()
    with _installed_lock:
        _installed = m
    return m


def uninstall_manifest() -> None:
    global _installed
    with _installed_lock:
        _installed = None


def installed() -> ShapeManifest | None:
    return _installed


def record_dispatch(inst, op: str, bucket: int, compile_key, cost_s: float, rows: int = 0) -> None:
    """EngineCache choke-point hook: record a first dispatch into the
    installed manifest, if any. Fake VDAFs are test machinery and never
    worth a prewarm slot; failures are swallowed — manifest trouble
    must never fail a serving dispatch."""
    m = _installed
    if m is None:
        return
    try:
        kind = getattr(inst, "kind", "")
        if kind.startswith("fake") or kind == "poplar1":
            return
        m.record(inst.to_dict(), op, bucket, compile_key, cost_s, rows=rows)
    except Exception:
        log.warning("shape manifest record failed", exc_info=True)


def inspect_file(path: str, max_entries: int = DEFAULT_MAX_ENTRIES) -> tuple[list[dict], dict]:
    """READ-ONLY parse of a manifest file: (entries, load stats) with
    no compaction, no rewrites, no side effects — for diagnostic tools
    (debug_bundle) that must inventory a live or damaged manifest
    without mutating the evidence."""
    m = ShapeManifest(path, max_entries=max_entries)  # no I/O until load
    stats = m.load(compact=False)
    return m.entries(), stats


def manifest_status() -> dict:
    """The manifest slice of the /statusz `engine_prewarm` section."""
    m = _installed
    if m is None:
        return {"installed": False}
    return {"installed": True, **m.status()}
