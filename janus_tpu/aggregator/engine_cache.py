"""Jitted device-step cache with batch-size bucketing.

One compiled executable serves many request sizes: batches are padded
up to the next power-of-two bucket (padding lanes carry mask=False and
are sliced off), so each (task VDAF, step kind) compiles O(log max
batch) times total. This is the TPU answer to the reference's
per-report loop — XLA sees static shapes, reports ride the batch axis.
"""

from __future__ import annotations

import os
import threading
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..vdaf.engine import STREAM_MIN_INPUT_LEN
from ..vdaf.registry import VdafInstance, prio3_batched

MIN_BUCKET = 32


def bucket_size(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _pad(arr, b: int):
    if arr is None:
        return None
    pad = b - arr.shape[0]
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(np.asarray(arr), widths)


def put_args(args, block: bool = False, shardings=None):
    """Explicitly dispatch every staged host array to the device, all
    puts in flight at once (async), before invoking the jit — one slow
    serialized arg upload must not gate the whole call.

    block=True waits for the transfers to land before returning:
    measured on the tunnel backend, dispatching an execute against
    still-pending input buffers degrades the transfer ~1.5-2x versus
    letting the puts finish first.

    shardings: optional pytree (matching args) of NamedShardings so
    multi-device placement happens in the transfer itself instead of a
    resharding copy at dispatch."""
    if shardings is not None:
        out = jax.device_put(args, shardings)
    else:
        out = jax.device_put(args)  # maps over the arg pytree, puts async
    if block:
        jax.block_until_ready(out)
    return out


def pad_args(b: int, *args):
    out = []
    for a in args:
        if a is None or isinstance(a, (bytes, int)):
            out.append(a)
        elif isinstance(a, tuple):  # field value limbs
            out.append(tuple(_pad(x, b) for x in a))
        else:
            out.append(_pad(a, b))
    return tuple(out)


class DeviceRows:
    """Out-share field value living ON DEVICE, padded to its bucket.

    The serving path used to fetch out shares to numpy after init and
    re-upload them for the masked aggregate — ~2x the out-share bytes
    across the host<->device link per job for nothing. Callers that
    truly need host rows (multi-round park paths) go through
    `to_numpy()`; `EngineCache.aggregate` consumes the device value
    directly.

    `offset` supports coalesced dispatches: several jobs' rows share
    one device buffer, each job holding a [offset, offset+n) view."""

    __slots__ = ("value", "n", "offset")

    def __init__(self, value, n: int, offset: int = 0):
        self.value = value  # tuple of [bucket, len] device limb arrays
        self.n = n  # true batch size (rows beyond n are padding)
        self.offset = offset

    def to_numpy(self):
        return tuple(
            np.asarray(x)[self.offset : self.offset + self.n] for x in self.value
        )


class DeviceRowsChunks:
    """Out shares of a pipelined (chunked) leader init: an ordered list
    of DeviceRows covering consecutive row ranges. Quacks like
    DeviceRows for the two consumers (to_numpy; EngineCache.aggregate
    special-cases it)."""

    __slots__ = ("chunks",)

    def __init__(self, chunks: list[DeviceRows]):
        self.chunks = chunks

    @property
    def n(self) -> int:
        return sum(c.n for c in self.chunks)

    def to_numpy(self):
        parts = [c.to_numpy() for c in self.chunks]
        return tuple(np.concatenate([p[i] for p in parts]) for i in range(len(parts[0])))


class _Coalescer:
    """Round-based dispatch coalescing across concurrent callers.

    The driver steps jobs concurrently but each job used to dispatch
    its own device call: a 10k-report Count job got 86,813 r/s from a
    chip that does 287,619 at batch 32768 (BASELINE.md matrix,
    VERDICT r4 weak #7) — the dispatch floor cannot amortize. Here
    concurrent calls to the same engine step merge into one padded
    device call: an arrival with no dispatch in flight goes out
    immediately (zero added latency when unloaded); arrivals during an
    in-flight dispatch queue and ride the next round together. The
    reference's analog is rayon parallelism inside one job
    (aggregation_job_driver.rs:329) — it has no cross-job batching at
    all.

    Lease/abandon semantics are untouched: coalescing sits strictly
    below the job layer (one device call serving several jobs' rows;
    each job still writes and releases its own lease).
    """

    __slots__ = ("_run", "_max_rows", "_lock", "_cv", "_queue", "_active", "rounds")

    def __init__(self, run, max_rows: int):
        import collections

        self._run = run  # ([args...], [n...]) -> [per-call results]
        self._max_rows = max_rows
        self._lock = threading.Lock()
        # signaled when the dispatcher role frees up with work queued
        self._cv = threading.Condition(self._lock)
        self._queue: list[list] = []  # entries: [args, n, Event, result, error]
        self._active = False
        # calls per dispatched round, recent window only (stats/tests;
        # unbounded growth would be a slow RSS leak on long-lived
        # aggregators)
        self.rounds = collections.deque(maxlen=1024)

    def submit(self, args, n: int):
        ent = [args, n, threading.Event(), None, None]
        with self._lock:
            self._queue.append(ent)
            dispatcher = not self._active
            if dispatcher:
                self._active = True
        if dispatcher:
            self._dispatch_until_done(ent)
        else:
            while not ent[2].is_set():
                # the previous dispatcher may exit with entries still
                # queued (its own round finished first): a waiter is
                # notified via the condition and adopts the role (the
                # short timeout is only a lost-wakeup backstop)
                with self._lock:
                    adopt = not self._active and not ent[2].is_set() and bool(self._queue)
                    if adopt:
                        self._active = True
                    elif not ent[2].is_set():
                        self._cv.wait(0.05)
                        continue
                if adopt:
                    self._dispatch_until_done(ent)
                    break
        if ent[4] is not None:
            raise ent[4]
        return ent[3]

    def _dispatch_until_done(self, own):
        """Dispatch rounds until our own entry completes AND the queue
        is drained or another thread adopts the role."""
        try:
            while True:
                with self._lock:
                    batch: list[list] = []
                    rows = 0
                    while self._queue and (
                        not batch or rows + self._queue[0][1] <= self._max_rows
                    ):
                        e = self._queue.pop(0)
                        batch.append(e)
                        rows += e[1]
                    if not batch:
                        return
                self.rounds.append(len(batch))
                try:
                    results = self._run([e[0] for e in batch], [e[1] for e in batch])
                    for e, r in zip(batch, results):
                        e[3] = r
                except BaseException as ex:  # noqa: BLE001 - even
                    # KeyboardInterrupt/SystemExit must release the
                    # co-batched waiters (their entries were already
                    # popped; nobody else will ever set their events)
                    for e in batch:
                        e[4] = ex
                    if not isinstance(ex, Exception):
                        for e in batch:
                            e[2].set()
                        with self._lock:
                            self._cv.notify_all()
                        raise
                for e in batch:
                    e[2].set()
                # wake cv-parked waiters so completed entries return
                # immediately instead of on the 50 ms backstop
                with self._lock:
                    self._cv.notify_all()
                if own[2].is_set():
                    # our caller has work to do with its result; hand
                    # the role to a waiter (notified in finally)
                    return
        finally:
            with self._lock:
                self._active = False
                if self._queue:
                    self._cv.notify()


def _concat_args(args_list):
    """Concatenate per-call arg tuples along the batch axis. None args
    must be None in every call (same engine => same schedule)."""
    out = []
    for parts in zip(*args_list):
        if parts[0] is None:
            assert all(p is None for p in parts)
            out.append(None)
        elif isinstance(parts[0], tuple):  # field limbs
            out.append(
                tuple(
                    np.concatenate([np.asarray(p[k]) for p in parts])
                    for k in range(len(parts[0]))
                )
            )
        else:
            assert all(p is not None for p in parts)
            out.append(np.concatenate([np.asarray(p) for p in parts]))
    return tuple(out)


def _split_rows(value, offsets):
    """Slice a host array / field tuple / None back into per-call rows."""
    if value is None:
        return [None] * (len(offsets) - 1)
    if isinstance(value, tuple):
        return [
            tuple(x[s:e] for x in value) for s, e in zip(offsets, offsets[1:])
        ]
    return [value[s:e] for s, e in zip(offsets, offsets[1:])]


class EngineCache:
    """Per (vdaf, verify_key) jitted steps, keyed by batch bucket.

    Multi-device serving: when the process sees more than one JAX
    device, every jitted step is bound to a dp (report-batch) mesh over
    the largest power-of-two device count, so helper init and the
    leader driver — the production traffic paths, not just bench.py —
    shard across chips (SURVEY §2.10 P2/P4; the reference scales the
    same work with DB replicas + rayon). Single-device behavior is
    unchanged."""

    # input_len at which the vector axis gets a slice of the mesh (sp):
    # the streamed-query activation point — the lengths where per-report
    # tensors, not report count, dominate
    SP_MIN_INPUT_LEN = STREAM_MIN_INPUT_LEN

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        self.inst = inst
        self.verify_key = verify_key
        self.p3 = prio3_batched(inst)
        self._jits: dict[str, object] = {}
        ndev = len(jax.devices())
        if ndev > 1:
            from ..parallel.api import make_mesh

            dp = 1 << (ndev.bit_length() - 1)  # largest power of two <= ndev
            sp = 1
            circ = self.p3.circ
            in_len = getattr(circ, "input_len", 0)
            out_len = getattr(circ, "output_len", 0)
            if (
                dp >= 2
                and in_len >= self.SP_MIN_INPUT_LEN
                and in_len % 2 == 0
                and out_len % 2 == 0
            ):
                # long-vector tasks: shard the measurement/out-share
                # columns too (SURVEY §2.10 P4 / §5 long-context analog)
                sp = 2
                dp //= 2
            dp = min(dp, MIN_BUCKET)  # every bucket must divide by dp
            self.mesh = make_mesh(dp, sp)
            self.dp = dp
            self.sp = sp
        else:
            self.mesh = None
            self.dp = 1
            self.sp = 1
        # cross-job dispatch coalescing (VERDICT r4 item 3): calls at or
        # below COALESCE_MAX_JOB rows ride shared device dispatches;
        # bigger jobs fill a dispatch on their own and go direct. The
        # per-round row cap scales inversely with the instance's
        # per-row size: a global 32768 tuned on Count would merge
        # concurrent SumVec jobs past the measured single-dispatch HBM
        # limit (len=1000 OOMs at batch 4096, BASELINE.md matrix) and
        # fail every co-batched job at once.
        self._coalesce = os.environ.get("JANUS_COALESCE", "1") != "0"
        in_len = max(1, getattr(self.p3.circ, "input_len", 1))
        round_rows = max(
            MIN_BUCKET, min(self.COALESCE_ROUND_ROWS, self.COALESCE_ROUND_ELEMS // in_len)
        )
        self._co_leader = _Coalescer(self._run_leader_round, round_rows)
        self._co_helper = _Coalescer(self._run_helper_round, round_rows)

    # Per-call row cap for joining a shared round; absolute round row
    # cap; and the rows x input_len budget one coalesced round may
    # stage (2^25 elements = half the len=1000 OOM point at 4096 rows).
    COALESCE_MAX_JOB = 4096
    COALESCE_ROUND_ROWS = 32768
    COALESCE_ROUND_ELEMS = 1 << 25

    def _shard(self, *batch_ndims):
        """NamedShardings splitting the leading (report) axis over 'dp';
        one entry per arg, each an int ndim or a tuple (field limbs) or
        None (absent arg). The string marker "vec2" is a 2-d field limb
        whose trailing (vector) axis additionally shards over 'sp'."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(nd):
            if nd is None:
                return None
            if nd == "vec2":
                return NamedSharding(self.mesh, P("dp", "sp"))
            if isinstance(nd, tuple):
                return tuple(one(x) for x in nd)
            return NamedSharding(self.mesh, P(*(("dp",) + (None,) * (nd - 1))))

        return tuple(one(nd) for nd in batch_ndims)

    def _jit(self, name: str, fn, in_shardings=None):
        if name not in self._jits:
            if self.mesh is not None and in_shardings is not None:
                self._jits[name] = jax.jit(fn, in_shardings=in_shardings)
            else:
                self._jits[name] = jax.jit(fn)
        return self._jits[name]

    # --- helper side: init + combine + decide in one traced step ---
    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        """Returns (out1 field value, accept mask, prep_msg lanes) sliced
        to the true batch size. Small batches coalesce with concurrent
        callers into one device dispatch (_Coalescer)."""
        n = nonce_lanes.shape[0]
        if self._coalesce and n <= self.COALESCE_MAX_JOB:
            return self._co_helper.submit(
                (nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask),
                n,
            )
        return self._helper_init_inner(
            nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask
        )

    def _run_helper_round(self, args_list, ns):
        offsets = list(np.cumsum([0] + ns))
        if len(args_list) == 1:
            out1, mask, prep_msg = self._helper_init_inner(*args_list[0])
            return [(out1, mask, prep_msg)]
        merged = _concat_args(args_list)
        out1, mask, prep_msg = self._helper_init_inner(*merged, coalesced=len(ns))
        return [
            (DeviceRows(out1.value, e - s, offset=s), mask[s:e], prep_msg[s:e])
            for s, e in zip(offsets, offsets[1:])
        ]

    def _helper_init_inner(
        self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask,
        coalesced: int = 0,
    ):
        p3 = self.p3
        n = nonce_lanes.shape[0]
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
            out1, seed1, ver1, part1 = p3.prepare_init_helper(
                self.verify_key, nonce_lanes, public_parts, helper_seeds, blinds
            )
            mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
            mask = p3.prepare_finish(seed1, prep_msg, mask)
            mask = mask & ok_mask
            if prep_msg is None:
                prep_msg = jnp.zeros((nonce_lanes.shape[0], 2), dtype=jnp.uint64)
            return out1, mask, prep_msg

        from ..trace import span

        L = len(ver0)
        shardings = None
        if self.mesh is not None:
            shardings = self._shard(
                2,
                None if public_parts is None else 3,
                2,
                None if blinds is None else 2,
                (2,) * L,
                2,
                1,
            )
        fn = self._jit("helper_init", step, in_shardings=shardings)
        args = pad_args(b, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask)
        # the np.asarray conversions block on device execution — they
        # must sit inside the span or it measures only async dispatch.
        # out1 stays ON DEVICE (DeviceRows): the aggregate step reads it
        # there; only the small mask/prep_msg come back.
        with span(
            "engine.helper_init",
            vdaf=self.inst.kind,
            batch=n,
            bucket=b,
            coalesced=coalesced,
        ):
            with span("engine.helper_init.put"):
                args = put_args(args, block=True, shardings=shardings)
            with span("engine.helper_init.dispatch"):
                out1, mask, prep_msg = fn(*args)
            with span("engine.helper_init.fetch"):
                mask = np.asarray(mask)[:n]
                prep_msg = np.asarray(prep_msg)[:n]
        return DeviceRows(out1, n), mask, prep_msg

    # Pipelined leader init: jobs past 2x this size split into chunks
    # whose host->device transfers are ALL issued up front; each chunk's
    # dispatch then overlaps the later chunks' transfers (VERDICT r3
    # item 8 — the driver used to stage-then-dispatch serially, leaving
    # the device idle for the whole staging transfer).
    PIPELINE_CHUNK = 256

    # --- leader side: init only (network round trip follows) ---
    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0, ok=None):
        # ok is accepted for interface parity with HostEngineCache; the
        # batched device step costs nothing extra for failed lanes
        # (their rows are zeroed and masked downstream).
        n = nonce_lanes.shape[0]
        if self._coalesce and n <= self.COALESCE_MAX_JOB:
            return self._co_leader.submit(
                (nonce_lanes, public_parts, meas, proof, blind0), n
            )
        return self._leader_init_inner(nonce_lanes, public_parts, meas, proof, blind0)

    def _run_leader_round(self, args_list, ns):
        offsets = list(np.cumsum([0] + ns))
        if len(args_list) == 1:
            return [self._leader_init_inner(*args_list[0])]
        merged = _concat_args(args_list)
        # one padded dispatch for the whole round (no intra-call
        # pipelining: round-to-round overlap already covers H2D)
        out0, seed0, ver0, part0 = self._leader_init_inner(
            *merged, coalesced=len(ns), allow_pipeline=False
        )
        outs = [
            DeviceRows(out0.value, e - s, offset=s)
            for s, e in zip(offsets, offsets[1:])
        ]
        seeds = _split_rows(seed0, offsets)
        vers = _split_rows(ver0, offsets)
        parts = _split_rows(part0, offsets)
        return list(zip(outs, seeds, vers, parts))

    def _leader_init_inner(
        self,
        nonce_lanes,
        public_parts,
        meas,
        proof,
        blind0,
        coalesced: int = 0,
        allow_pipeline: bool = True,
    ):
        p3 = self.p3
        n = nonce_lanes.shape[0]
        if allow_pipeline and self.mesh is None and n >= 2 * self.PIPELINE_CHUNK:
            return self._leader_init_pipelined(
                nonce_lanes, public_parts, meas, proof, blind0
            )
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, meas, proof, blind0):
            return p3.prepare_init_leader(
                self.verify_key, nonce_lanes, public_parts, meas, proof, blind0
            )

        from ..trace import span

        L = len(meas)
        shardings = None
        if self.mesh is not None:
            meas_nd = "vec2" if self.sp > 1 else 2
            shardings = self._shard(
                2,
                None if public_parts is None else 3,
                (meas_nd,) * L,
                (2,) * L,
                None if blind0 is None else 2,
            )
        fn = self._jit("leader_init", step, in_shardings=shardings)
        args = pad_args(b, nonce_lanes, public_parts, meas, proof, blind0)
        # conversions block on device execution — keep inside the span.
        # out0 stays ON DEVICE (DeviceRows) for the later aggregate;
        # seed0/ver0/part0 are needed host-side for the wire round trip.
        with span(
            "engine.leader_init",
            vdaf=self.inst.kind,
            batch=n,
            bucket=b,
            coalesced=coalesced,
        ):
            with span("engine.leader_init.put"):
                args = put_args(args, block=True, shardings=shardings)
            with span("engine.leader_init.dispatch"):
                out0, seed0, ver0, part0 = fn(*args)
            with span("engine.leader_init.fetch_seed"):
                seed0 = np.asarray(seed0)[:n] if seed0 is not None else None
            with span("engine.leader_init.fetch_ver"):
                ver0 = tuple(np.asarray(x)[:n] for x in ver0)
            with span("engine.leader_init.fetch_part"):
                part0 = np.asarray(part0)[:n] if part0 is not None else None
        return DeviceRows(out0, n), seed0, ver0, part0

    def _leader_init_pipelined(self, nonce_lanes, public_parts, meas, proof, blind0):
        """Chunked leader init: every chunk's device transfer is issued
        immediately (async, all in flight), then chunks dispatch in
        order — chunk k's compute overlaps chunk k+1..'s H2D. Outputs
        are host-concatenated; out shares stay device-resident as
        DeviceRowsChunks."""
        import jax

        from ..trace import span

        p3 = self.p3
        n = nonce_lanes.shape[0]
        C = self.PIPELINE_CHUNK

        def step(nonce_lanes, public_parts, meas, proof, blind0):
            return p3.prepare_init_leader(
                self.verify_key, nonce_lanes, public_parts, meas, proof, blind0
            )

        fn = self._jit("leader_init", step)

        def cut(a, s, e):
            if a is None:
                return None
            if isinstance(a, tuple):
                return tuple(x[s:e] for x in a)
            return a[s:e]

        spans_ = [(s, min(s + C, n)) for s in range(0, n, C)]
        with span("engine.leader_init", vdaf=self.inst.kind, batch=n, pipelined=len(spans_)):
            staged = []
            with span("engine.leader_init.put_all_async"):
                for s, e in spans_:
                    args = pad_args(
                        bucket_size(e - s),
                        cut(nonce_lanes, s, e),
                        cut(public_parts, s, e),
                        cut(meas, s, e),
                        cut(proof, s, e),
                        cut(blind0, s, e),
                    )
                    staged.append(put_args(args, block=False))
            outs = []
            for k, ((s, e), args) in enumerate(zip(spans_, staged)):
                with span("engine.leader_init.chunk", k=k, rows=e - s):
                    jax.block_until_ready(args)  # this chunk's H2D only
                    outs.append(fn(*args))
            with span("engine.leader_init.fetch"):
                out_chunks = [
                    DeviceRows(o[0], e - s) for (s, e), o in zip(spans_, outs)
                ]
                seed0 = (
                    np.concatenate(
                        [np.asarray(o[1])[: e - s] for (s, e), o in zip(spans_, outs)]
                    )
                    if outs[0][1] is not None
                    else None
                )
                L = len(outs[0][2])
                ver0 = tuple(
                    np.concatenate(
                        [np.asarray(o[2][i])[: e - s] for (s, e), o in zip(spans_, outs)]
                    )
                    for i in range(L)
                )
                part0 = (
                    np.concatenate(
                        [np.asarray(o[3])[: e - s] for (s, e), o in zip(spans_, outs)]
                    )
                    if outs[0][3] is not None
                    else None
                )
        return DeviceRowsChunks(out_chunks), seed0, ver0, part0

    # --- masked aggregate over the batch axis ---
    def aggregate(self, out_shares, mask):
        p3 = self.p3

        if isinstance(out_shares, DeviceRowsChunks):
            # chunked out shares: per-chunk masked reduce, host merge
            p = p3.jf.MODULUS
            total = None
            off = 0
            for chunk in out_shares.chunks:
                part = self.aggregate(chunk, np.asarray(mask)[off : off + chunk.n])
                off += chunk.n
                total = part if total is None else [
                    (a + b) % p for a, b in zip(total, part)
                ]
            return total

        def step(out_shares, mask):
            return p3.aggregate(out_shares, mask)

        fn = self._jit("aggregate", step)
        if isinstance(out_shares, DeviceRows):
            # device-resident path: the out shares are already on device
            # padded to their bucket — only the (tiny) mask moves.
            n = out_shares.n
            value = out_shares.value
            b = value[0].shape[0]
            vb = bucket_size(n)
            s = out_shares.offset
            if (s or vb < b) and s + vb <= b:
                # coalesced view: one jitted dynamic-slice + masked
                # reduce over the job's own bucket — reducing the whole
                # merged buffer once per co-batched job would multiply
                # the aggregate work by the round size. (Views whose
                # bucket would run past the buffer keep the full-width
                # mask path below: dynamic_slice clamps out-of-bounds
                # starts, which would silently shift rows.)
                def step_view(value, start, mask, _vb=vb):
                    v = tuple(
                        jax.lax.dynamic_slice_in_dim(x, start, _vb, axis=0)
                        for x in value
                    )
                    return p3.aggregate(v, mask)

                fnv = self._jit(f"aggregate_view_{vb}", step_view)
                mask_vb = np.zeros(vb, dtype=bool)
                mask_vb[:n] = np.asarray(mask, dtype=bool)
                agg = fnv(value, np.int32(s), mask_vb)
            else:
                full = np.zeros(b, dtype=bool)
                full[s : s + n] = np.asarray(mask, dtype=bool)
                agg = fn(value, full)
        else:
            n = mask.shape[0]
            b = bucket_size(n)
            agg = fn(*pad_args(b, out_shares, mask))
        return [int(x) for x in p3.jf.to_ints(agg)]


class _HostP3:
    """Duck-typed `.p3` for HostEngineCache (callers use engine.p3.jf
    for the columnar codecs)."""

    def __init__(self, jf):
        self.jf = jf


class HostEngineCache:
    """Per-report host engine for draft-mode (spec-framing) tasks.

    Same surface as EngineCache but loops reports through the scalar
    host Prio3 — mirroring the reference's own per-report CPU loop
    (aggregation_job_driver.rs:329-402, aggregator.rs:1775-1826). The
    TPU engine only implements the fast framing; conformant tasks trade
    throughput for cross-implementation compatibility.
    """

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        from ..vdaf.engine import jf_for
        from ..vdaf.registry import circuit_for, prio3_host

        self.inst = inst
        self.verify_key = verify_key
        self.host = prio3_host(inst)
        self.circ = circuit_for(inst)
        self.jf = jf_for(self.circ)
        self.p3 = _HostP3(self.jf)

    # --- lane <-> host-int conversions ---
    def _row_ints(self, limbs, i) -> list[int]:
        if len(limbs) == 1:
            return [int(x) for x in np.asarray(limbs[0])[i]]
        lo = np.asarray(limbs[0])[i]
        hi = np.asarray(limbs[1])[i]
        return [int(l) | (int(h) << 64) for l, h in zip(lo, hi)]

    def _ints_to_limbs(self, rows: list[list[int] | None], n: int):
        batch = len(rows)
        out = tuple(np.zeros((batch, n), dtype=np.uint64) for _ in range(self.jf.LIMBS))
        for i, r in enumerate(rows):
            if r is None:
                continue
            for j, v in enumerate(r):
                out[0][i, j] = np.uint64(v & 0xFFFFFFFFFFFFFFFF)
                if self.jf.LIMBS == 2:
                    out[1][i, j] = np.uint64(v >> 64)
        return out

    @staticmethod
    def _row_bytes(lanes, i) -> bytes:
        return np.asarray(lanes, dtype="<u8")[i].tobytes()

    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        from ..vdaf.reference import HelperShare, PrepShare, VdafError

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        accept = np.zeros(n, dtype=bool)
        prep_msg = np.zeros((n, 2), dtype=np.uint64)
        for i in range(n):
            if not ok_mask[i]:
                continue
            nonce = self._row_bytes(nonce_lanes, i)
            share = HelperShare(
                self._row_bytes(helper_seeds, i),
                self._row_bytes(blinds, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            try:
                state1, ps1 = self.host.prepare_init(
                    self.verify_key, 1, nonce, parts, share
                )
                ps0 = PrepShare(
                    self._row_ints(ver0, i),
                    self._row_bytes(part0, i) if uses_jr else None,
                )
                msg = self.host.prepare_shares_to_prep([ps0, ps1])
                self.host.prepare_next(state1, msg)
            except VdafError:
                continue
            out_rows[i] = state1.out_share
            accept[i] = True
            if uses_jr:
                prep_msg[i] = np.frombuffer(msg, dtype="<u8")
        out1 = self._ints_to_limbs(out_rows, self.circ.output_len)
        return out1, accept, prep_msg

    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0, ok=None):
        from ..vdaf.reference import LeaderShare

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        ver_rows: list[list[int] | None] = [None] * n
        seed0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        part0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        for i in range(n):
            if ok is not None and not ok[i]:
                continue  # don't pay scalar FLP prepare for failed lanes
            nonce = self._row_bytes(nonce_lanes, i)
            share = LeaderShare(
                self._row_ints(meas, i),
                self._row_ints(proof, i),
                self._row_bytes(blind0, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            state, ps = self.host.prepare_init(self.verify_key, 0, nonce, parts, share)
            out_rows[i] = state.out_share
            ver_rows[i] = ps.verifier_share
            if uses_jr:
                seed0[i] = np.frombuffer(state.corrected_joint_rand_seed, dtype="<u8")
                part0[i] = np.frombuffer(ps.joint_rand_part, dtype="<u8")
        out0 = self._ints_to_limbs(out_rows, self.circ.output_len)
        ver0 = self._ints_to_limbs(ver_rows, self.circ.verifier_len)
        return out0, seed0, ver0, part0

    def aggregate(self, out_shares, mask):
        p = self.circ.FIELD.MODULUS
        agg = [0] * self.circ.output_len
        for i in range(mask.shape[0]):
            if not mask[i]:
                continue
            row = self._row_ints(out_shares, i)
            agg = [(a + b) % p for a, b in zip(agg, row)]
        return agg


@lru_cache(maxsize=256)
def engine_cache(inst: VdafInstance, verify_key: bytes):
    if inst.xof_mode != "fast":
        # draft (VDAF-07) framing: device engine for every circuit
        # whose sponge streams fit vdaf.draft_jax MAX_STREAM_BLOCKS
        # (160k since r5 — covers the north-star len=100k; the r4
        # "latency knee" was a flat-scan pathology, BASELINE.md "Draft
        # mode"); truly huge streams keep the scalar host loop
        try:
            prio3_batched(inst)
        except ValueError:
            return HostEngineCache(inst, verify_key)
    return EngineCache(inst, verify_key)
