"""Jitted device-step cache with batch-size bucketing.

One compiled executable serves many request sizes: batches are padded
up to the next power-of-two bucket (padding lanes carry mask=False and
are sliced off), so each (task VDAF, step kind) compiles O(log max
batch) times total. This is the TPU answer to the reference's
per-report loop — XLA sees static shapes, reports ride the batch axis.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..vdaf.registry import VdafInstance, prio3_batched

MIN_BUCKET = 32


def bucket_size(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _pad(arr, b: int):
    if arr is None:
        return None
    pad = b - arr.shape[0]
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(np.asarray(arr), widths)


def pad_args(b: int, *args):
    out = []
    for a in args:
        if a is None or isinstance(a, (bytes, int)):
            out.append(a)
        elif isinstance(a, tuple):  # field value limbs
            out.append(tuple(_pad(x, b) for x in a))
        else:
            out.append(_pad(a, b))
    return tuple(out)


class EngineCache:
    """Per (vdaf, verify_key) jitted steps, keyed by batch bucket."""

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        self.inst = inst
        self.verify_key = verify_key
        self.p3 = prio3_batched(inst)
        self._jits: dict[str, object] = {}

    def _jit(self, name: str, fn):
        if name not in self._jits:
            self._jits[name] = jax.jit(fn)
        return self._jits[name]

    # --- helper side: init + combine + decide in one traced step ---
    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        """Returns (out1 field value, accept mask, prep_msg lanes) sliced
        to the true batch size."""
        p3 = self.p3
        n = nonce_lanes.shape[0]
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
            out1, seed1, ver1, part1 = p3.prepare_init_helper(
                self.verify_key, nonce_lanes, public_parts, helper_seeds, blinds
            )
            mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
            mask = p3.prepare_finish(seed1, prep_msg, mask)
            mask = mask & ok_mask
            if prep_msg is None:
                prep_msg = jnp.zeros((nonce_lanes.shape[0], 2), dtype=jnp.uint64)
            return out1, mask, prep_msg

        fn = self._jit("helper_init", step)
        args = pad_args(b, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask)
        out1, mask, prep_msg = fn(*args)
        out1 = tuple(np.asarray(x)[:n] for x in out1)
        return out1, np.asarray(mask)[:n], np.asarray(prep_msg)[:n]

    # --- leader side: init only (network round trip follows) ---
    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0):
        p3 = self.p3
        n = nonce_lanes.shape[0]
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, meas, proof, blind0):
            return p3.prepare_init_leader(
                self.verify_key, nonce_lanes, public_parts, meas, proof, blind0
            )

        fn = self._jit("leader_init", step)
        args = pad_args(b, nonce_lanes, public_parts, meas, proof, blind0)
        out0, seed0, ver0, part0 = fn(*args)
        out0 = tuple(np.asarray(x)[:n] for x in out0)
        seed0 = np.asarray(seed0)[:n] if seed0 is not None else None
        ver0 = tuple(np.asarray(x)[:n] for x in ver0)
        part0 = np.asarray(part0)[:n] if part0 is not None else None
        return out0, seed0, ver0, part0

    # --- masked aggregate over the batch axis ---
    def aggregate(self, out_shares, mask):
        p3 = self.p3
        n = mask.shape[0]
        b = bucket_size(n)

        def step(out_shares, mask):
            return p3.aggregate(out_shares, mask)

        fn = self._jit("aggregate", step)
        agg = fn(*pad_args(b, out_shares, mask))
        return [int(x) for x in p3.jf.to_ints(agg)]


@lru_cache(maxsize=256)
def engine_cache(inst: VdafInstance, verify_key: bytes) -> EngineCache:
    return EngineCache(inst, verify_key)
