"""Jitted device-step cache with batch-size bucketing.

One compiled executable serves many request sizes: batches are padded
up to the next power-of-two bucket (padding lanes carry mask=False and
are sliced off), so each (task VDAF, step kind) compiles O(log max
batch) times total. This is the TPU answer to the reference's
per-report loop — XLA sees static shapes, reports ride the batch axis.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..vdaf.registry import VdafInstance, prio3_batched

MIN_BUCKET = 32


def bucket_size(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _pad(arr, b: int):
    if arr is None:
        return None
    pad = b - arr.shape[0]
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(np.asarray(arr), widths)


def pad_args(b: int, *args):
    out = []
    for a in args:
        if a is None or isinstance(a, (bytes, int)):
            out.append(a)
        elif isinstance(a, tuple):  # field value limbs
            out.append(tuple(_pad(x, b) for x in a))
        else:
            out.append(_pad(a, b))
    return tuple(out)


class EngineCache:
    """Per (vdaf, verify_key) jitted steps, keyed by batch bucket."""

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        self.inst = inst
        self.verify_key = verify_key
        self.p3 = prio3_batched(inst)
        self._jits: dict[str, object] = {}

    def _jit(self, name: str, fn):
        if name not in self._jits:
            self._jits[name] = jax.jit(fn)
        return self._jits[name]

    # --- helper side: init + combine + decide in one traced step ---
    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        """Returns (out1 field value, accept mask, prep_msg lanes) sliced
        to the true batch size."""
        p3 = self.p3
        n = nonce_lanes.shape[0]
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
            out1, seed1, ver1, part1 = p3.prepare_init_helper(
                self.verify_key, nonce_lanes, public_parts, helper_seeds, blinds
            )
            mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
            mask = p3.prepare_finish(seed1, prep_msg, mask)
            mask = mask & ok_mask
            if prep_msg is None:
                prep_msg = jnp.zeros((nonce_lanes.shape[0], 2), dtype=jnp.uint64)
            return out1, mask, prep_msg

        from ..trace import span

        fn = self._jit("helper_init", step)
        args = pad_args(b, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask)
        # the np.asarray conversions block on device execution — they
        # must sit inside the span or it measures only async dispatch
        with span("engine.helper_init", vdaf=self.inst.kind, batch=n, bucket=b):
            out1, mask, prep_msg = fn(*args)
            out1 = tuple(np.asarray(x)[:n] for x in out1)
            mask = np.asarray(mask)[:n]
            prep_msg = np.asarray(prep_msg)[:n]
        return out1, mask, prep_msg

    # --- leader side: init only (network round trip follows) ---
    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0, ok=None):
        # ok is accepted for interface parity with HostEngineCache; the
        # batched device step costs nothing extra for failed lanes
        # (their rows are zeroed and masked downstream).
        p3 = self.p3
        n = nonce_lanes.shape[0]
        b = bucket_size(n)

        def step(nonce_lanes, public_parts, meas, proof, blind0):
            return p3.prepare_init_leader(
                self.verify_key, nonce_lanes, public_parts, meas, proof, blind0
            )

        from ..trace import span

        fn = self._jit("leader_init", step)
        args = pad_args(b, nonce_lanes, public_parts, meas, proof, blind0)
        # conversions block on device execution — keep inside the span
        with span("engine.leader_init", vdaf=self.inst.kind, batch=n, bucket=b):
            out0, seed0, ver0, part0 = fn(*args)
            out0 = tuple(np.asarray(x)[:n] for x in out0)
            seed0 = np.asarray(seed0)[:n] if seed0 is not None else None
            ver0 = tuple(np.asarray(x)[:n] for x in ver0)
            part0 = np.asarray(part0)[:n] if part0 is not None else None
        return out0, seed0, ver0, part0

    # --- masked aggregate over the batch axis ---
    def aggregate(self, out_shares, mask):
        p3 = self.p3
        n = mask.shape[0]
        b = bucket_size(n)

        def step(out_shares, mask):
            return p3.aggregate(out_shares, mask)

        fn = self._jit("aggregate", step)
        agg = fn(*pad_args(b, out_shares, mask))
        return [int(x) for x in p3.jf.to_ints(agg)]


class _HostP3:
    """Duck-typed `.p3` for HostEngineCache (callers use engine.p3.jf
    for the columnar codecs)."""

    def __init__(self, jf):
        self.jf = jf


class HostEngineCache:
    """Per-report host engine for draft-mode (spec-framing) tasks.

    Same surface as EngineCache but loops reports through the scalar
    host Prio3 — mirroring the reference's own per-report CPU loop
    (aggregation_job_driver.rs:329-402, aggregator.rs:1775-1826). The
    TPU engine only implements the fast framing; conformant tasks trade
    throughput for cross-implementation compatibility.
    """

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        from ..vdaf.engine import jf_for
        from ..vdaf.registry import circuit_for, prio3_host

        self.inst = inst
        self.verify_key = verify_key
        self.host = prio3_host(inst)
        self.circ = circuit_for(inst)
        self.jf = jf_for(self.circ)
        self.p3 = _HostP3(self.jf)

    # --- lane <-> host-int conversions ---
    def _row_ints(self, limbs, i) -> list[int]:
        if len(limbs) == 1:
            return [int(x) for x in np.asarray(limbs[0])[i]]
        lo = np.asarray(limbs[0])[i]
        hi = np.asarray(limbs[1])[i]
        return [int(l) | (int(h) << 64) for l, h in zip(lo, hi)]

    def _ints_to_limbs(self, rows: list[list[int] | None], n: int):
        batch = len(rows)
        out = tuple(np.zeros((batch, n), dtype=np.uint64) for _ in range(self.jf.LIMBS))
        for i, r in enumerate(rows):
            if r is None:
                continue
            for j, v in enumerate(r):
                out[0][i, j] = np.uint64(v & 0xFFFFFFFFFFFFFFFF)
                if self.jf.LIMBS == 2:
                    out[1][i, j] = np.uint64(v >> 64)
        return out

    @staticmethod
    def _row_bytes(lanes, i) -> bytes:
        return np.asarray(lanes, dtype="<u8")[i].tobytes()

    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        from ..vdaf.reference import HelperShare, PrepShare, VdafError

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        accept = np.zeros(n, dtype=bool)
        prep_msg = np.zeros((n, 2), dtype=np.uint64)
        for i in range(n):
            if not ok_mask[i]:
                continue
            nonce = self._row_bytes(nonce_lanes, i)
            share = HelperShare(
                self._row_bytes(helper_seeds, i),
                self._row_bytes(blinds, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            try:
                state1, ps1 = self.host.prepare_init(
                    self.verify_key, 1, nonce, parts, share
                )
                ps0 = PrepShare(
                    self._row_ints(ver0, i),
                    self._row_bytes(part0, i) if uses_jr else None,
                )
                msg = self.host.prepare_shares_to_prep([ps0, ps1])
                self.host.prepare_next(state1, msg)
            except VdafError:
                continue
            out_rows[i] = state1.out_share
            accept[i] = True
            if uses_jr:
                prep_msg[i] = np.frombuffer(msg, dtype="<u8")
        out1 = self._ints_to_limbs(out_rows, self.circ.output_len)
        return out1, accept, prep_msg

    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0, ok=None):
        from ..vdaf.reference import LeaderShare

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        ver_rows: list[list[int] | None] = [None] * n
        seed0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        part0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        for i in range(n):
            if ok is not None and not ok[i]:
                continue  # don't pay scalar FLP prepare for failed lanes
            nonce = self._row_bytes(nonce_lanes, i)
            share = LeaderShare(
                self._row_ints(meas, i),
                self._row_ints(proof, i),
                self._row_bytes(blind0, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            state, ps = self.host.prepare_init(self.verify_key, 0, nonce, parts, share)
            out_rows[i] = state.out_share
            ver_rows[i] = ps.verifier_share
            if uses_jr:
                seed0[i] = np.frombuffer(state.corrected_joint_rand_seed, dtype="<u8")
                part0[i] = np.frombuffer(ps.joint_rand_part, dtype="<u8")
        out0 = self._ints_to_limbs(out_rows, self.circ.output_len)
        ver0 = self._ints_to_limbs(ver_rows, self.circ.verifier_len)
        return out0, seed0, ver0, part0

    def aggregate(self, out_shares, mask):
        p = self.circ.FIELD.MODULUS
        agg = [0] * self.circ.output_len
        for i in range(mask.shape[0]):
            if not mask[i]:
                continue
            row = self._row_ints(out_shares, i)
            agg = [(a + b) % p for a, b in zip(agg, row)]
        return agg


@lru_cache(maxsize=256)
def engine_cache(inst: VdafInstance, verify_key: bytes):
    if inst.xof_mode != "fast":
        return HostEngineCache(inst, verify_key)
    return EngineCache(inst, verify_key)
